"""WAL recycling (recycle_log_file_num + recyclable record format) and
archival (wal_ttl_seconds) — reference include/rocksdb/options.h:795 and
WalManager retention (VERDICT r2 missing #7)."""

import os

from toplingdb_tpu.db.db import DB
from toplingdb_tpu.options import Options


def _wal_files(d):
    return sorted(f for f in os.listdir(d) if f.endswith(".log"))


def test_recycled_wal_reused_and_recovery_clean(tmp_path):
    d = str(tmp_path / "db")
    opts = Options(create_if_missing=True, write_buffer_size=4 * 1024,
                   recycle_log_file_num=2)
    with DB.open(d, opts) as db:
        # Several memtable switches → several WAL generations; obsolete
        # ones enter the recycle pool instead of being deleted.
        for i in range(4000):
            db.put(b"key%05d" % i, b"val%06d" % i)
        db.flush()
        pool = list(db._recycle_wals)
        assert pool, "no WALs were recycled"
        # Write more: a recycled file gets REUSED (same number disappears
        # from the pool, its bytes overwritten in place).
        for i in range(4000, 5000):
            db.put(b"key%05d" % i, b"val%06d" % i)
    # Recovery: the reused WAL's stale tail (previous life) must read as
    # end-of-log, not replay into the wrong state.
    with DB.open(d, opts) as db2:
        for i in range(0, 5000, 97):
            assert db2.get(b"key%05d" % i) == b"val%06d" % i
        it = db2.new_iterator()
        it.seek_to_first()
        assert sum(1 for _ in it.entries()) == 5000


def test_recycled_stale_tail_longer_than_new_life(tmp_path):
    """A reused WAL whose previous life was LONGER than the new one: the
    leftover records must not replay (log-number stamp mismatch)."""
    from toplingdb_tpu.db.log import LogReader, LogWriter
    from toplingdb_tpu.env import default_env

    env = default_env()
    p1 = str(tmp_path / "000007.log")
    w = env.new_writable_file(p1)
    lw = LogWriter(w, log_number=7, recycled=True)
    for i in range(2000):  # several 32KiB blocks: the stale tail spans
        lw.add_record(b"old-record-%04d" % i * 10)  # block boundaries
    lw.close()
    # Reuse as log 9: write just TWO records over the front.
    p2 = str(tmp_path / "000009.log")
    w2 = env.reuse_writable_file(p1, p2)
    lw2 = LogWriter(w2, log_number=9, recycled=True)
    lw2.add_record(b"new-a")
    lw2.add_record(b"new-b")
    lw2.flush()
    lw2.close()
    r = LogReader(env.new_sequential_file(p2), log_number=9)
    assert list(r.records()) == [b"new-a", b"new-b"]


def test_wal_archival_and_ttl(tmp_path, monkeypatch):
    d = str(tmp_path / "db")
    opts = Options(create_if_missing=True, write_buffer_size=4 * 1024,
                   wal_ttl_seconds=3600.0)
    with DB.open(d, opts) as db:
        for i in range(4000):
            db.put(b"key%05d" % i, b"v%05d" % i)
        db.flush()
        arch = os.path.join(d, "archive")
        assert os.path.isdir(arch) and os.listdir(arch), "no archived WALs"
        files = db.get_wal_files()
        assert any(a for _n, _p, a in files), "archived WALs not listed"
        assert any(not a for _n, _p, a in files), "live WAL not listed"
        # Age the archived files past the TTL: next archival purges them.
        for f in os.listdir(arch):
            p = os.path.join(arch, f)
            os.utime(p, (1, 1))
        for i in range(4000, 9000):
            db.put(b"key%05d" % i, b"v%05d" % i)
        db.flush()
        old = [f for f in os.listdir(arch)
               if os.path.getmtime(os.path.join(arch, f)) < 1000]
        assert not old, "TTL-expired archived WALs survived"


def test_ldb_wal_dump_recycled_log(tmp_path, capsys):
    """ldb dump_wal passes the log number, so a recycled WAL dumps only
    its CURRENT life's records."""
    from toplingdb_tpu.db.log import LogWriter
    from toplingdb_tpu.env import default_env
    from toplingdb_tpu.db.write_batch import WriteBatch
    from toplingdb_tpu.tools import ldb

    env = default_env()
    p1 = str(tmp_path / "000004.log")
    w = env.new_writable_file(p1)
    lw = LogWriter(w, log_number=4, recycled=True)
    for i in range(800):
        b = WriteBatch()
        b.put(b"old%04d" % i, b"x" * 40)
        b.set_sequence(i + 1)
        lw.add_record(b.data())
    lw.close()
    p2 = str(tmp_path / "000009.log")
    w2 = env.reuse_writable_file(p1, p2)
    lw2 = LogWriter(w2, log_number=9, recycled=True)
    b = WriteBatch()
    b.put(b"new-key", b"new-val")
    b.set_sequence(500)
    lw2.add_record(b.data())
    lw2.flush()
    lw2.close()
    rc = ldb.main(["--db", str(tmp_path), "wal_dump", p2])
    assert rc == 0
    out = capsys.readouterr().out
    assert "new-key" in out
    assert "old0000" not in out, "previous-life records dumped as live"
