"""Pallas kernel: shared-prefix lengths vs a Python reference."""

import numpy as np
import pytest

from toplingdb_tpu.ops.pallas_kernels import shared_prefix_lengths


def ref_prefix(keys: list[bytes]) -> list[int]:
    out = [0]
    for a, b in zip(keys, keys[1:]):
        n = 0
        for x, y in zip(a, b):
            if x != y:
                break
            n += 1
        out.append(n)
    return out


def to_matrix(keys, k=32):
    m = np.zeros((len(keys), k), dtype=np.uint8)
    for i, key in enumerate(keys):
        m[i, : len(key)] = np.frombuffer(key, dtype=np.uint8)
    return m, np.array([len(key) for key in keys], dtype=np.int32)


def test_prefix_kernel_matches_reference():
    keys = sorted(
        b"key%05d" % (i * 7 % 1000) for i in range(500)
    )
    m, lens = to_matrix(keys)
    got = shared_prefix_lengths(m, lens)
    assert got.tolist() == ref_prefix(keys)


def test_prefix_kernel_zero_padding_not_counted():
    # "ab" vs "ab\x00cd": zero padding of the shorter key must not extend
    # the shared prefix beyond its true length.
    keys = [b"ab", b"ab\x00cd"]
    m, lens = to_matrix(keys, k=8)
    got = shared_prefix_lengths(m, lens)
    assert got.tolist() == [0, 2]


def test_prefix_kernel_random():
    import random

    rng = random.Random(3)
    keys = sorted({rng.randbytes(rng.randint(1, 30)) for _ in range(700)})
    m, lens = to_matrix(keys)
    got = shared_prefix_lengths(m, lens)
    assert got.tolist() == ref_prefix(keys)


def test_prefix_kernel_single_and_empty():
    m, lens = to_matrix([b"solo"])
    assert shared_prefix_lengths(m, lens).tolist() == [0]


def test_gc_rows_matches_lax_mask():
    """pallas_kernels.gc_rows (interpret mode on CPU) must agree with the
    lax formulation of stripe / first-in-stripe / tombstone shadowing /
    complex flags for random sorted streams with snapshots+tombstones."""
    import jax.numpy as jnp
    import numpy as np

    from toplingdb_tpu.ops import pallas_kernels as pk

    rng = np.random.default_rng(5)
    n, s = 2048, 64
    seq = np.sort(rng.integers(0, 1 << 40, n).astype(np.uint64))[::-1]
    snaps = np.sort(rng.integers(0, 1 << 40, 5).astype(np.uint64))
    snap_pad = np.full(s, 1 << 56, np.uint64)
    snap_pad[:5] = snaps
    tomb = np.where(rng.random(n) < 0.3,
                    rng.integers(0, 1 << 40, n).astype(np.uint64),
                    np.uint64(0))
    vtype = rng.choice([0, 1, 2, 7], n).astype(np.int32)
    new_key = rng.random(n) < 0.4
    new_key[0] = True

    hi = lambda x: (x >> np.uint64(32)).astype(np.uint32)
    lo = lambda x: (x & np.uint64(0xFFFFFFFF)).astype(np.uint32)
    pseq = np.roll(seq, 1)
    stripe, fis, covered, cx = pk.gc_rows(
        jnp.asarray(hi(seq)), jnp.asarray(lo(seq)),
        jnp.asarray(hi(pseq)), jnp.asarray(lo(pseq)),
        jnp.asarray(new_key), jnp.asarray(hi(tomb)), jnp.asarray(lo(tomb)),
        jnp.asarray(vtype), jnp.asarray(hi(snap_pad)),
        jnp.asarray(lo(snap_pad)), interpret=True,
    )
    # numpy reference
    want_stripe = np.searchsorted(snap_pad, seq, side="left")
    want_fis = new_key | (want_stripe != np.roll(want_stripe, 1))
    tomb_stripe = np.searchsorted(snap_pad, tomb, side="left")
    want_cov = (tomb != 0) & (tomb > seq) & (tomb_stripe == want_stripe)
    want_cx = (vtype == 2) | (vtype == 7)
    assert np.array_equal(np.asarray(stripe), want_stripe)
    assert np.array_equal(np.asarray(fis) | new_key, want_fis | new_key)
    assert np.array_equal(np.asarray(covered), want_cov)
    assert np.array_equal(np.asarray(cx), want_cx)
