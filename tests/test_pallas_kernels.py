"""Pallas kernel: shared-prefix lengths vs a Python reference."""

import numpy as np
import pytest

from toplingdb_tpu.ops.pallas_kernels import shared_prefix_lengths


def ref_prefix(keys: list[bytes]) -> list[int]:
    out = [0]
    for a, b in zip(keys, keys[1:]):
        n = 0
        for x, y in zip(a, b):
            if x != y:
                break
            n += 1
        out.append(n)
    return out


def to_matrix(keys, k=32):
    m = np.zeros((len(keys), k), dtype=np.uint8)
    for i, key in enumerate(keys):
        m[i, : len(key)] = np.frombuffer(key, dtype=np.uint8)
    return m, np.array([len(key) for key in keys], dtype=np.int32)


def test_prefix_kernel_matches_reference():
    keys = sorted(
        b"key%05d" % (i * 7 % 1000) for i in range(500)
    )
    m, lens = to_matrix(keys)
    got = shared_prefix_lengths(m, lens)
    assert got.tolist() == ref_prefix(keys)


def test_prefix_kernel_zero_padding_not_counted():
    # "ab" vs "ab\x00cd": zero padding of the shorter key must not extend
    # the shared prefix beyond its true length.
    keys = [b"ab", b"ab\x00cd"]
    m, lens = to_matrix(keys, k=8)
    got = shared_prefix_lengths(m, lens)
    assert got.tolist() == [0, 2]


def test_prefix_kernel_random():
    import random

    rng = random.Random(3)
    keys = sorted({rng.randbytes(rng.randint(1, 30)) for _ in range(700)})
    m, lens = to_matrix(keys)
    got = shared_prefix_lengths(m, lens)
    assert got.tolist() == ref_prefix(keys)


def test_prefix_kernel_single_and_empty():
    m, lens = to_matrix([b"solo"])
    assert shared_prefix_lengths(m, lens).tolist() == [0]
