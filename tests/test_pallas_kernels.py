"""Pallas kernel: shared-prefix lengths vs a Python reference."""

import numpy as np
import pytest

from toplingdb_tpu.ops.pallas_kernels import shared_prefix_lengths


def ref_prefix(keys: list[bytes]) -> list[int]:
    out = [0]
    for a, b in zip(keys, keys[1:]):
        n = 0
        for x, y in zip(a, b):
            if x != y:
                break
            n += 1
        out.append(n)
    return out


def to_matrix(keys, k=32):
    m = np.zeros((len(keys), k), dtype=np.uint8)
    for i, key in enumerate(keys):
        m[i, : len(key)] = np.frombuffer(key, dtype=np.uint8)
    return m, np.array([len(key) for key in keys], dtype=np.int32)


def test_prefix_kernel_matches_reference():
    keys = sorted(
        b"key%05d" % (i * 7 % 1000) for i in range(500)
    )
    m, lens = to_matrix(keys)
    got = shared_prefix_lengths(m, lens)
    assert got.tolist() == ref_prefix(keys)


def test_prefix_kernel_zero_padding_not_counted():
    # "ab" vs "ab\x00cd": zero padding of the shorter key must not extend
    # the shared prefix beyond its true length.
    keys = [b"ab", b"ab\x00cd"]
    m, lens = to_matrix(keys, k=8)
    got = shared_prefix_lengths(m, lens)
    assert got.tolist() == [0, 2]


def test_prefix_kernel_random():
    import random

    rng = random.Random(3)
    keys = sorted({rng.randbytes(rng.randint(1, 30)) for _ in range(700)})
    m, lens = to_matrix(keys)
    got = shared_prefix_lengths(m, lens)
    assert got.tolist() == ref_prefix(keys)


def test_prefix_kernel_single_and_empty():
    m, lens = to_matrix([b"solo"])
    assert shared_prefix_lengths(m, lens).tolist() == [0]


def test_gc_rows_matches_lax_mask():
    """pallas_kernels.gc_rows (interpret mode on CPU) must agree with the
    lax formulation of stripe / first-in-stripe / tombstone shadowing /
    complex flags for random sorted streams with snapshots+tombstones."""
    import jax.numpy as jnp
    import numpy as np

    from toplingdb_tpu.ops import pallas_kernels as pk

    rng = np.random.default_rng(5)
    n, s = 2048, 64
    seq = np.sort(rng.integers(0, 1 << 40, n).astype(np.uint64))[::-1]
    snaps = np.sort(rng.integers(0, 1 << 40, 5).astype(np.uint64))
    snap_pad = np.full(s, 1 << 56, np.uint64)
    snap_pad[:5] = snaps
    tomb = np.where(rng.random(n) < 0.3,
                    rng.integers(0, 1 << 40, n).astype(np.uint64),
                    np.uint64(0))
    vtype = rng.choice([0, 1, 2, 7], n).astype(np.int32)
    new_key = rng.random(n) < 0.4
    new_key[0] = True

    hi = lambda x: (x >> np.uint64(32)).astype(np.uint32)
    lo = lambda x: (x & np.uint64(0xFFFFFFFF)).astype(np.uint32)
    pseq = np.roll(seq, 1)
    stripe, fis, covered, cx = pk.gc_rows(
        jnp.asarray(hi(seq)), jnp.asarray(lo(seq)),
        jnp.asarray(hi(pseq)), jnp.asarray(lo(pseq)),
        jnp.asarray(new_key), jnp.asarray(hi(tomb)), jnp.asarray(lo(tomb)),
        jnp.asarray(vtype), jnp.asarray(hi(snap_pad)),
        jnp.asarray(lo(snap_pad)), interpret=True,
    )
    # numpy reference
    want_stripe = np.searchsorted(snap_pad, seq, side="left")
    want_fis = new_key | (want_stripe != np.roll(want_stripe, 1))
    tomb_stripe = np.searchsorted(snap_pad, tomb, side="left")
    want_cov = (tomb != 0) & (tomb > seq) & (tomb_stripe == want_stripe)
    want_cx = (vtype == 2) | (vtype == 7)
    assert np.array_equal(np.asarray(stripe), want_stripe)
    assert np.array_equal(np.asarray(fis) | new_key, want_fis | new_key)
    assert np.array_equal(np.asarray(covered), want_cov)
    assert np.array_equal(np.asarray(cx), want_cx)


def test_bitonic_merge_pair_parity():
    """Kernel-backed pairwise merge == numpy lexsort over the key words
    (4-column internal-key shape: key_hi, key_lo, inv_hi, inv_lo)."""
    from toplingdb_tpu.ops.pallas_kernels import bitonic_merge_pair

    rng = np.random.default_rng(11)
    for na, nb in ((0, 7), (7, 0), (1000, 1000), (1237, 777),
                   (5000, 12000)):
        def mk(n):
            cols = [rng.integers(0, 1 << 32, n, dtype=np.uint64)
                    .astype(np.uint32) for _ in range(4)]
            order = np.lexsort(tuple(reversed(cols)))
            return [c[order] for c in cols]

        a, b = mk(na), mk(nb)
        pm = bitonic_merge_pair(a, b, interpret=True)
        cat = [np.concatenate([x, y]) for x, y in zip(a, b)]
        want = np.lexsort(tuple(reversed(cat)))
        got_keys = np.stack([c[pm] for c in cat])
        want_keys = np.stack([c[want] for c in cat])
        assert np.array_equal(got_keys, want_keys), (na, nb)


def test_bitonic_merge_runs_parity_with_host_merge():
    """Segmented multi-run kernel merge realizes the SAME order as the
    native host merge (the flagship compaction order) on 8B-key runs."""
    from toplingdb_tpu.ops import compaction_kernels as ck
    from toplingdb_tpu.ops.pallas_kernels import bitonic_merge_runs

    rng = np.random.default_rng(12)
    n_runs, per = 4, 3000
    keys = []
    starts = [0]
    for r in range(n_runs):
        draws = rng.integers(0, 4000, per)
        seqs = np.arange(r * per + 1, r * per + per + 1, dtype=np.uint64)
        order = np.lexsort(
            (np.iinfo(np.int64).max - seqs.view(np.int64), draws))
        for i in order:
            packed = (int(seqs[i]) << 8) | 1
            keys.append(b"%08d" % draws[i] + packed.to_bytes(8, "little"))
        starts.append(len(keys))
    buf = np.frombuffer(b"".join(keys), np.uint8)
    offs = np.arange(len(keys), dtype=np.int64) * 16
    lens = np.full(len(keys), 16, np.int64)
    nat = ck.host_sort_order(buf, offs, lens,
                             run_starts=np.array(starts, np.int64))
    assert nat is not None
    want_order = nat[0]
    # Column encoding: BE key words ascending, then INVERTED packed
    # (seq desc) — the device sort's order.
    kb = buf.reshape(len(keys), 16)
    key_hi = kb[:, :4].copy().view(">u4").reshape(-1).astype(np.uint32)
    key_lo = kb[:, 4:8].copy().view(">u4").reshape(-1).astype(np.uint32)
    packed = kb[:, 8:16].copy().view("<u8").reshape(-1)
    inv = ~packed
    inv_hi = (inv >> np.uint64(32)).astype(np.uint32)
    inv_lo = (inv & np.uint64(0xFFFFFFFF)).astype(np.uint32)
    pm = bitonic_merge_runs([key_hi, key_lo, inv_hi, inv_lo], starts,
                            interpret=True)
    assert np.array_equal(pm, want_order)


def test_bitonic_merge_stability_on_equal_keys():
    """Equal keys come out in concat(A, B) order — the tiebreak column
    makes the (inherently unstable) bitonic network stable."""
    from toplingdb_tpu.ops.pallas_kernels import bitonic_merge_pair

    a = [np.zeros(3, np.uint32)]
    b = [np.zeros(4, np.uint32)]
    pm = bitonic_merge_pair(a, b, interpret=True)
    assert pm.tolist() == [0, 1, 2, 3, 4, 5, 6]


def test_bitonic_merge_runs_oversized_pair_falls_back():
    from toplingdb_tpu.ops import pallas_kernels as pk

    rng = np.random.default_rng(3)
    old = pk._BITONIC_MAX_ROWS
    pk._BITONIC_MAX_ROWS = 1 << 10  # force the host fallback path
    try:
        n = 4096
        col = np.sort(rng.integers(0, 1 << 20, n).astype(np.uint32)
                      .reshape(2, n // 2), axis=1).reshape(n)
        starts = [0, n // 2, n]
        pm = pk.bitonic_merge_runs([col], starts, interpret=True)
        assert np.array_equal(col[pm], np.sort(col))
    finally:
        pk._BITONIC_MAX_ROWS = old
