import pytest

from toplingdb_tpu.db.dbformat import InternalKeyComparator, ValueType
from toplingdb_tpu.db.memtable import MemTable
from toplingdb_tpu.db.write_batch import WriteBatch
from toplingdb_tpu.utils.status import Corruption


def test_batch_encode_iterate():
    b = WriteBatch()
    b.put(b"k1", b"v1")
    b.delete(b"k2")
    b.merge(b"k3", b"m1")
    b.single_delete(b"k4")
    b.delete_range(b"a", b"z")
    b.put_log_data(b"annotation")  # not counted
    assert b.count() == 5
    got = list(b.entries())
    assert got == [
        (ValueType.VALUE, b"k1", b"v1"),
        (ValueType.DELETION, b"k2", None),
        (ValueType.MERGE, b"k3", b"m1"),
        (ValueType.SINGLE_DELETION, b"k4", None),
        (ValueType.RANGE_DELETION, b"a", b"z"),
    ]


def test_batch_roundtrip_bytes():
    b = WriteBatch()
    b.put(b"key", b"value")
    b.set_sequence(42)
    b2 = WriteBatch(b.data())
    assert b2.sequence() == 42
    assert b2.count() == 1
    assert list(b2.entries()) == list(b.entries())


def test_batch_append_from():
    a = WriteBatch()
    a.put(b"k1", b"v1")
    b = WriteBatch()
    b.put(b"k2", b"v2")
    a.append_from(b)
    assert a.count() == 2
    assert [k for _, k, _ in a.entries()] == [b"k1", b"k2"]


def test_count_mismatch_detected():
    b = WriteBatch()
    b.put(b"k", b"v")
    b.set_count(3)
    with pytest.raises(Corruption):
        list(b.entries())


def test_insert_into_memtable_assigns_seqnos():
    b = WriteBatch()
    b.put(b"ka", b"v1")
    b.put(b"kb", b"v2")
    b.set_sequence(10)
    mem = MemTable(InternalKeyComparator())
    consumed = b.insert_into(mem)
    assert consumed == 2
    entries = list(mem.entries_for_key(b"ka", 2**56 - 1))
    assert entries == [(10, ValueType.VALUE, b"v1")]
    entries = list(mem.entries_for_key(b"kb", 2**56 - 1))
    assert entries == [(11, ValueType.VALUE, b"v2")]
