import pytest

from toplingdb_tpu.db.log import BLOCK_SIZE, LogReader, LogWriter
from toplingdb_tpu.env import MemEnv
from toplingdb_tpu.utils.status import Corruption


def roundtrip(env, records, path="/wal"):
    w = LogWriter(env.new_writable_file(path))
    for r in records:
        w.add_record(r)
    w.sync()
    return list(LogReader(env.new_sequential_file(path)).records())


def test_simple_roundtrip(mem_env):
    recs = [b"hello", b"", b"world" * 100]
    assert roundtrip(mem_env, recs) == recs


def test_record_spanning_blocks(mem_env):
    big = bytes(range(256)) * 512  # 128 KiB > 4 blocks
    recs = [b"small", big, b"tail"]
    assert roundtrip(mem_env, recs) == recs


def test_block_boundary_padding(mem_env):
    # A record sized to leave <7 bytes in the block forces padding.
    rec = b"x" * (BLOCK_SIZE - 7 - 3)
    recs = [rec, b"second"]
    assert roundtrip(mem_env, recs) == recs


def test_torn_tail_is_dropped(mem_env):
    w = LogWriter(mem_env.new_writable_file("/wal"))
    w.add_record(b"committed-1")
    w.sync()
    w.add_record(b"torn-write")
    # No sync: crash loses the tail.
    mem_env.drop_unsynced()
    # Even partial loss of the last record must not corrupt earlier ones.
    got = list(LogReader(mem_env.new_sequential_file("/wal")).records())
    assert got[0] == b"committed-1"
    assert len(got) <= 2


def test_truncated_mid_record(mem_env):
    w = LogWriter(mem_env.new_writable_file("/wal"))
    w.add_record(b"a" * 100)
    w.add_record(b"b" * 100)
    w.sync()
    st = mem_env._files["/wal"]
    del st.data[len(st.data) - 50 :]  # cut into record 2
    got = list(LogReader(mem_env.new_sequential_file("/wal")).records())
    assert got == [b"a" * 100]


def test_corrupt_crc_raises(mem_env):
    w = LogWriter(mem_env.new_writable_file("/wal"))
    w.add_record(b"a" * 100)
    w.add_record(b"b" * 100)
    # Pad the file past one block so the corrupt record is not "at eof".
    w.add_record(b"c" * BLOCK_SIZE)
    w.sync()
    st = mem_env._files["/wal"]
    st.data[10] ^= 0xFF  # corrupt payload of record 1
    r = LogReader(mem_env.new_sequential_file("/wal"))
    with pytest.raises(Corruption):
        list(r.records())
