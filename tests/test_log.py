import pytest

from toplingdb_tpu.db.log import BLOCK_SIZE, LogReader, LogWriter
from toplingdb_tpu.env import MemEnv
from toplingdb_tpu.utils.status import Corruption


def roundtrip(env, records, path="/wal"):
    w = LogWriter(env.new_writable_file(path))
    for r in records:
        w.add_record(r)
    w.sync()
    return list(LogReader(env.new_sequential_file(path)).records())


def test_simple_roundtrip(mem_env):
    recs = [b"hello", b"", b"world" * 100]
    assert roundtrip(mem_env, recs) == recs


def test_record_spanning_blocks(mem_env):
    big = bytes(range(256)) * 512  # 128 KiB > 4 blocks
    recs = [b"small", big, b"tail"]
    assert roundtrip(mem_env, recs) == recs


def test_block_boundary_padding(mem_env):
    # A record sized to leave <7 bytes in the block forces padding.
    rec = b"x" * (BLOCK_SIZE - 7 - 3)
    recs = [rec, b"second"]
    assert roundtrip(mem_env, recs) == recs


def test_torn_tail_is_dropped(mem_env):
    w = LogWriter(mem_env.new_writable_file("/wal"))
    w.add_record(b"committed-1")
    w.sync()
    w.add_record(b"torn-write")
    # No sync: crash loses the tail.
    mem_env.drop_unsynced()
    # Even partial loss of the last record must not corrupt earlier ones.
    got = list(LogReader(mem_env.new_sequential_file("/wal")).records())
    assert got[0] == b"committed-1"
    assert len(got) <= 2


def test_truncated_mid_record(mem_env):
    w = LogWriter(mem_env.new_writable_file("/wal"))
    w.add_record(b"a" * 100)
    w.add_record(b"b" * 100)
    w.sync()
    st = mem_env._files["/wal"]
    del st.data[len(st.data) - 50 :]  # cut into record 2
    got = list(LogReader(mem_env.new_sequential_file("/wal")).records())
    assert got == [b"a" * 100]


def test_corrupt_crc_raises(mem_env):
    w = LogWriter(mem_env.new_writable_file("/wal"))
    w.add_record(b"a" * 100)
    w.add_record(b"b" * 100)
    # Pad the file past one block so the corrupt record is not "at eof".
    w.add_record(b"c" * BLOCK_SIZE)
    w.sync()
    st = mem_env._files["/wal"]
    st.data[10] ^= 0xFF  # corrupt payload of record 1
    r = LogReader(mem_env.new_sequential_file("/wal"))
    with pytest.raises(Corruption):
        list(r.records())


# -- tailing-tolerant reader (replication WAL shipping) ----------------------


def test_tailing_reader_incremental(mem_env):
    from toplingdb_tpu.db.log import TailingLogReader

    w = LogWriter(mem_env.new_writable_file("/wal"))
    tr = TailingLogReader(mem_env, "/wal")
    assert tr.poll() == []
    w.add_record(b"one")
    w.sync()
    assert tr.poll() == [b"one"]
    assert tr.poll() == []  # no new bytes
    w.add_record(b"two")
    w.add_record(b"three" * 100)
    w.sync()
    assert tr.poll() == [b"two", b"three" * 100]


def test_tailing_reader_spanning_blocks(mem_env):
    from toplingdb_tpu.db.log import TailingLogReader

    w = LogWriter(mem_env.new_writable_file("/wal"))
    tr = TailingLogReader(mem_env, "/wal")
    big = bytes(range(256)) * 512  # > 4 blocks: FIRST/MIDDLE/LAST chain
    w.add_record(b"small")
    w.add_record(big)
    w.sync()
    assert tr.poll() == [b"small", big]


def test_tailing_torn_tail_retries_then_completes(mem_env):
    """A partial trailing record is NOT corruption: poll() holds position
    and delivers the record once the writer finishes it."""
    from toplingdb_tpu.db.log import TailingLogReader

    w = LogWriter(mem_env.new_writable_file("/wal"))
    w.add_record(b"committed")
    w.sync()
    st = mem_env._files["/wal"]
    full = bytes(st.data)
    w.add_record(b"torn-in-flight")
    whole = bytes(st.data)
    # Roll back to a torn state: half the new record's bytes are missing.
    cut = len(full) + (len(whole) - len(full)) // 2
    del st.data[cut:]
    tr = TailingLogReader(mem_env, "/wal")
    assert tr.poll() == [b"committed"]  # torn tail parked, not raised
    assert tr.poll() == []              # still parked
    st.data += whole[cut:]              # writer finishes the append
    assert tr.poll() == [b"torn-in-flight"]


def test_tailing_torn_tail_dropped_on_final(mem_env):
    from toplingdb_tpu.db.log import TailingLogReader

    w = LogWriter(mem_env.new_writable_file("/wal"))
    w.add_record(b"committed")
    w.add_record(b"torn")
    st = mem_env._files["/wal"]
    del st.data[len(st.data) - 3 :]  # crash cut the tail
    tr = TailingLogReader(mem_env, "/wal")
    assert tr.poll(final=True) == [b"committed"]
    assert tr.poll(final=True) == []


def test_tailing_corrupt_middle_raises(mem_env):
    """A checksum mismatch with durable bytes AFTER it can never be an
    in-flight append: the tailing reader must fail loudly, not ship it."""
    from toplingdb_tpu.db.log import TailingLogReader

    w = LogWriter(mem_env.new_writable_file("/wal"))
    w.add_record(b"a" * 100)
    w.add_record(b"b" * 100)
    w.add_record(b"c" * BLOCK_SIZE)  # push the damage away from EOF
    w.sync()
    st = mem_env._files["/wal"]
    st.data[10] ^= 0xFF
    tr = TailingLogReader(mem_env, "/wal")
    with pytest.raises(Corruption):
        tr.poll()


def test_tailing_corrupt_at_tail_is_torn_not_corrupt(mem_env):
    from toplingdb_tpu.db.log import TailingLogReader

    w = LogWriter(mem_env.new_writable_file("/wal"))
    w.add_record(b"good")
    w.add_record(b"bad-tail")
    w.sync()
    st = mem_env._files["/wal"]
    st.data[-2] ^= 0xFF  # flip a byte in the LAST record's payload
    tr = TailingLogReader(mem_env, "/wal")
    # Live tail: could be an append still in flight — park, don't raise.
    assert tr.poll() == [b"good"]
    assert tr.poll() == []
