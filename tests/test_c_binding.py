"""The flat C API (toplingdb_tpu/bindings/c — the reference's db/c.cc role):
compile the shared lib + demo with the system toolchain and drive the full
open/put/get/delete/flush/reopen cycle from C."""

import os
import shutil
import subprocess
import sys

import pytest

ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
CDIR = os.path.join(ROOT, "toplingdb_tpu", "bindings", "c")


@pytest.mark.skipif(
    shutil.which("g++") is None or shutil.which("gcc") is None
    or shutil.which("python3-config") is None,
    reason="C toolchain unavailable",
)
def test_c_binding_end_to_end(tmp_path):
    lib = os.path.join(CDIR, "libtpulsm_c.so")
    demo = str(tmp_path / "demo")
    subprocess.run(
        f"g++ -shared -fPIC -O2 tpulsm_c.c -o libtpulsm_c.so "
        f"$(python3-config --includes) $(python3-config --ldflags --embed)",
        shell=True, cwd=CDIR, check=True,
    )
    subprocess.run(
        f"gcc -O2 demo.c -o {demo} -I{CDIR} -L{CDIR} -ltpulsm_c "
        f"-Wl,-rpath,{CDIR}",
        shell=True, cwd=CDIR, check=True,
    )
    env = dict(os.environ)
    # The embedded interpreter needs the repo (and the jax plugin dir when
    # present) on PYTHONPATH; the C caller never imports jax.
    pypath = ROOT
    if os.path.isdir("/root/.axon_site"):
        pypath += ":/root/.axon_site"
    env["PYTHONPATH"] = pypath
    out = subprocess.run(
        [demo, str(tmp_path / "cdb")], env=env, capture_output=True,
        timeout=120,
    )
    assert out.returncode == 0, out.stderr.decode()
    assert b"C-API-OK" in out.stdout
    assert os.path.exists(lib)
