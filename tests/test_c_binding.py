"""The flat C API (toplingdb_tpu/bindings/c — the reference's db/c.cc role):
compile the shared lib + demo with the system toolchain and drive the full
open/put/get/delete/flush/reopen cycle from C."""

import os
import shutil
import subprocess
import sys

import pytest

ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
CDIR = os.path.join(ROOT, "toplingdb_tpu", "bindings", "c")



def _build_lib_and_env(tmp_path, demo_src, demo_name):
    """Build libtpulsm_c.so once per call + the given demo; returns
    (demo_path, env) — shared by every C-binding test so the compile
    flags cannot diverge between them."""
    demo = str(tmp_path / demo_name)
    subprocess.run(
        f"g++ -shared -fPIC -O2 tpulsm_c.c -o libtpulsm_c.so "
        f"$(python3-config --includes) $(python3-config --ldflags --embed)",
        shell=True, cwd=CDIR, check=True,
    )
    subprocess.run(
        f"gcc -O2 {demo_src} -o {demo} -I{CDIR} -L{CDIR} -ltpulsm_c "
        f"-Wl,-rpath,{CDIR}",
        shell=True, cwd=CDIR, check=True,
    )
    env = dict(os.environ)
    # The embedded interpreter needs the repo (and the jax plugin dir when
    # present) on PYTHONPATH; the C caller never imports jax.
    pypath = ROOT
    if os.path.isdir("/root/.axon_site"):
        pypath += ":/root/.axon_site"
    env["PYTHONPATH"] = pypath
    return demo, env

@pytest.mark.skipif(
    shutil.which("g++") is None or shutil.which("gcc") is None
    or shutil.which("python3-config") is None,
    reason="C toolchain unavailable",
)
def test_c_binding_end_to_end(tmp_path):
    demo, env = _build_lib_and_env(tmp_path, "demo.c", "demo")
    out = subprocess.run(
        [demo, str(tmp_path / "cdb")], env=env, capture_output=True,
        timeout=120,
    )
    assert out.returncode == 0, out.stderr.decode()
    assert b"C-API-OK" in out.stdout
    assert os.path.exists(os.path.join(CDIR, "libtpulsm_c.so"))


@pytest.mark.skipif(
    shutil.which("g++") is None or shutil.which("gcc") is None
    or shutil.which("python3-config") is None,
    reason="C toolchain unavailable",
)
def test_c_repo_open_from_json_and_http(tmp_path):
    """SidePluginRepo through the C ABI: open-from-JSON-config, write/read,
    HTTP introspection (/dbs), close-all — the reference's
    SidePluginRepo.java open-from-config flow."""
    demo, env = _build_lib_and_env(tmp_path, "repo_demo.c", "repo_demo")
    out = subprocess.run(
        [demo, str(tmp_path / "repodb")], env=env, capture_output=True,
        timeout=120,
    )
    assert out.returncode == 0, out.stderr.decode()
    assert b"REPO-C-API-OK" in out.stdout
