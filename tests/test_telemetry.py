"""Telemetry plane (utils/telemetry.py + the instrumentation it feeds):
tracer unit behavior, write/get/flush/compaction span trees, native
interior timings, cross-process stitching (dcompact HTTP worker,
replication follower acks incl. the dropped-ack degradation), the
/metrics–/traces–/stats_history HTTP surface, PerfContext chunk-path
parity, the IOStats Env feed, event-log correlation + ldb dump_events,
and the check_telemetry name lint."""

import json
import os
import re
import time
import urllib.request

import pytest

from toplingdb_tpu.db.db import DB
from toplingdb_tpu.options import Options, ReadOptions, WriteOptions
from toplingdb_tpu.utils import statistics as st
from toplingdb_tpu.utils import telemetry as tm
from toplingdb_tpu.utils.statistics import Statistics


def topts(**kw):
    kw.setdefault("create_if_missing", True)
    kw.setdefault("trace_sample_every", 1)
    return Options(**kw)


def fill(db, n=300, vlen=24):
    for i in range(n):
        db.put(b"key%06d" % i, b"v" * vlen)


# ---------------------------------------------------------------------------
# Tracer unit behavior
# ---------------------------------------------------------------------------


def test_sampling_one_in_n_and_ring_bound():
    tr = tm.Tracer(sample_every=4, ring=8)
    done = 0
    for _ in range(64):
        sp = tr.maybe_sample("db.get")
        if sp is not None:
            sp.finish()
            done += 1
    assert done == 16
    s = tr.status()
    assert s["traces_retained"] == 8  # ring bound, not 16
    assert s["traces_started"] == 16
    assert len(tr._by_id) == 8  # the stitch index tracks the ring
    assert not tr._active


def test_slow_backstop_and_slow_filter():
    tr = tm.Tracer(sample_every=0, slow_usec=1000)
    tr.note_slow("db.get", 5000, key="k")
    fast = tm.Tracer(sample_every=1, slow_usec=10_000_000)
    sp = fast.start("db.write")
    sp.finish()
    assert [t.slow for t in tr.finished()] == [True]
    assert [t.slow for t in fast.finished()] == [False]
    assert tr.finished(slow_only=True)[0].dur_us == 5000


def test_span_tree_and_chrome_export():
    tr = tm.Tracer(sample_every=1)
    root = tr.start("db.write", records=3)
    with tm.span("write.wal_frame", group=2):
        time.sleep(0.002)
        tm.span_event("native.wal_frame", 1500, bytes=64)
    root.finish()
    t = tr.finished()[0]
    names = [s.name for s in t.spans]
    assert names == ["db.write", "write.wal_frame", "native.wal_frame"]
    wal = t.spans[1]
    assert wal.parent_id == t.root.span_id and wal.dur_us >= 2000
    chrome = tr.chrome_trace(t.trace_id)
    assert {e["name"] for e in chrome["traceEvents"]} == set(names)
    assert all(e["ph"] == "X" and e["dur"] >= 1
               for e in chrome["traceEvents"])
    assert chrome["otherData"]["trace_id"] == t.trace_id
    json.dumps(chrome)  # exportable


def test_cross_thread_span_under_and_remote_stitch():
    tr = tm.Tracer(sample_every=1, proc="db")
    root = tr.start("compaction")
    handle = tm.current_handle()
    sp = tm.span_under(handle, "pipeline.merge_gc", shard=3)
    sp.finish()
    tm.span_event_under(handle, "pipeline.scan", 777, shard=0)
    root.finish()
    # Remote spans: known trace stitches, evicted/unknown drops silently.
    n = tr.attach_remote([
        {"name": "dcompact.worker", "trace_id": root.trace_id,
         "span_id": 1, "parent_id": root.span_id, "start_us": 0,
         "dur_us": 5, "proc": "dcompact-worker", "tags": {}},
        {"name": "dcompact.worker", "trace_id": "feedfacedeadbeef",
         "dur_us": 5},
    ])
    assert n == 1
    t = tr.get_trace(root.trace_id)
    assert {s.name for s in t.spans} == {
        "compaction", "pipeline.merge_gc", "pipeline.scan",
        "dcompact.worker"}
    assert {s.proc for s in t.spans} == {"db", "dcompact-worker"}
    assert tr.status()["remote_spans_dropped"] == 1


# ---------------------------------------------------------------------------
# Engine instrumentation: write / get / flush / compaction
# ---------------------------------------------------------------------------


def test_write_get_flush_span_trees(tmp_path):
    db = DB.open(str(tmp_path / "db"), topts(statistics=Statistics()))
    try:
        fill(db, 200)
        assert db.get(b"key000007") == b"v" * 24
        db.multi_get([b"key000001", b"key000002"])
        db.flush()
        traces = {t.name: t for t in db.tracer.finished(limit=300)}
        assert {"db.write", "db.get", "db.multiget", "flush"} <= set(traces)
        wt = traces["db.write"]
        wnames = {s.name for s in wt.spans}
        assert "write.wal_frame" in wnames
        assert "write.memtable_apply" in wnames
        ft = traces["flush"]
        assert "flush.build_table" in {s.name for s in ft.spans}
        # seq → ctx propagation map is populated and bounded
        assert db.tracer.status()["seq_ctx_entries"] <= 1024
        assert db.tracer.ctxs_in_range(1, 10)
    finally:
        db.close()


def test_native_interior_spans_when_plane_available(tmp_path):
    from toplingdb_tpu import native

    if native.lib() is None:
        pytest.skip("no native lib")
    db = DB.open(str(tmp_path / "db"), topts())
    try:
        from toplingdb_tpu.db.write_batch import WriteBatch

        b = WriteBatch()
        for i in range(50):
            b.put(b"nk%05d" % i, b"v" * 32)
        db.write(b)
        wt = [t for t in db.tracer.finished(limit=50)
              if t.name == "db.write"][0]
        names = {s.name for s in wt.spans}
        if db._write_plane:  # plane resolved: interiors must surface
            assert "native.memtable_insert" in names
    finally:
        db.close()


def test_compaction_trace_modes_and_phases(tmp_path):
    db = DB.open(str(tmp_path / "db"),
                 topts(write_buffer_size=16 << 10,
                       statistics=Statistics()))
    try:
        for i in range(1200):
            db.put(b"c%06d" % (i % 400), b"v%06d" % i)
            if i % 300 == 299:
                db.flush()
        db.compact_range()
        comps = [t for t in db.tracer.finished(limit=300)
                 if t.name == "compaction"]
        assert comps
        t = comps[0]
        assert t.root.tags.get("mode") in (
            "serial", "columnar", "device", "pipelined", "remote")
        child_names = {s.name for s in t.spans} - {"compaction"}
        assert child_names & {
            "compaction.subcompaction", "compaction.input_scan",
            "compaction.compute", "compaction.encode_write",
            "pipeline.scan", "pipeline.merge_gc",
            "pipeline.encode_write"}
    finally:
        db.close()


def test_trace_ring_is_bounded_under_load(tmp_path):
    db = DB.open(str(tmp_path / "db"), topts(trace_ring=16))
    try:
        fill(db, 400)
        s = db.tracer.status()
        assert s["traces_retained"] <= 16
        assert len(db.tracer._by_id) <= 16
        assert s["traces_active"] == 0
    finally:
        db.close()


def test_slow_unsampled_write_leaves_root_trace(tmp_path):
    db = DB.open(str(tmp_path / "db"),
                 Options(create_if_missing=True, trace_sample_every=0,
                         trace_slow_usec=1))
    try:
        db.put(b"a", b"b")  # any write beats a 1µs threshold
        ts = db.tracer.finished()
        assert ts and ts[0].slow and ts[0].name == "db.write"
        assert len(ts[0].spans) == 1  # root-only backstop
    finally:
        db.close()


# ---------------------------------------------------------------------------
# Cross-process: dcompact HTTP worker stitching
# ---------------------------------------------------------------------------


def test_dcompact_http_job_stitches_worker_spans(tmp_path, monkeypatch):
    from toplingdb_tpu.compaction.dcompact_service import (
        DcompactWorkerService, HttpCompactionExecutorFactory,
    )
    from toplingdb_tpu.compaction.resilience import DcompactOptions
    from toplingdb_tpu.ops import pipeline as pl

    # Engage the 3-stage pipeline inside the (in-process) worker so the
    # stitched waterfall is of a PIPELINED remote job (the acceptance
    # shape) — the row floor would route a test-sized job serial.
    monkeypatch.setattr(pl, "MIN_PIPELINE_ROWS", 256)
    monkeypatch.setenv("TPULSM_PIPELINE_SHARDS", "4")
    svc = DcompactWorkerService(device="cpu-jax")
    port = svc.start()
    fac = HttpCompactionExecutorFactory(
        [f"http://127.0.0.1:{port}"],
        policy=DcompactOptions(max_attempts=2, lease_sec=5.0))
    db = DB.open(str(tmp_path / "db"),
                 topts(write_buffer_size=1 << 14,
                       disable_auto_compactions=True,
                       compaction_executor_factory=fac,
                       statistics=Statistics()))
    try:
        for i in range(2400):
            db.put(b"key%05d" % (i % 800), b"val%07d" % i)
            if i % 300 == 299:
                db.flush()
        db.flush()
        db.compact_range()
        assert db.get(b"key00799") == b"val%07d" % 2399
        comps = [t for t in db.tracer.finished(limit=300)
                 if t.name == "compaction"]
        stitched = [t for t in comps
                    if any(s.proc == "dcompact-worker" for s in t.spans)]
        assert stitched, "no compaction trace carries worker spans"
        t = stitched[0]
        worker_spans = [s for s in t.spans if s.proc == "dcompact-worker"]
        names = {s.name for s in worker_spans}
        assert "dcompact.worker" in names
        # every worker span belongs to the SAME trace id (one waterfall)
        assert {s.trace_id for s in worker_spans} == {t.trace_id}
        # the worker root parents under the DB-side compaction root
        wroot = next(s for s in worker_spans
                     if s.name == "dcompact.worker")
        assert wroot.parent_id == t.root.span_id
        assert t.root.tags.get("mode") == "remote"
        # the PIPELINED interior stages recorded inside the worker:
        # per-shard scan/merge spans plus writer chunks
        assert {"pipeline.scan", "pipeline.merge_gc"} <= names
    finally:
        db.close()
        svc.stop()


# ---------------------------------------------------------------------------
# Cross-process: replication follower ack stitching + dropped-ack
# ---------------------------------------------------------------------------


def test_replication_write_stitches_follower_apply(tmp_path):
    from toplingdb_tpu.replication.follower import FollowerDB
    from toplingdb_tpu.replication.log_shipper import (
        LocalTransport, LogShipper,
    )

    src = str(tmp_path / "db")
    db = DB.open(src, topts(statistics=Statistics()))
    fol = None
    try:
        ship = LogShipper(db)
        fol = FollowerDB.open(src, transport=LocalTransport(ship),
                              mode="shared")
        db.put(b"rk1", b"rv1", WriteOptions(sync=True))
        db.put(b"rk2", b"rv2")
        assert fol.catch_up() > 0      # applies + banks the spans
        assert fol._span_outbox
        fol.catch_up()                 # the ack pull ships them back
        assert not fol._span_outbox
        writes = [t for t in db.tracer.finished(limit=100)
                  if t.name == "db.write"]
        stitched = [t for t in writes
                    if any(s.name == "follower.apply" for s in t.spans)]
        assert stitched, "no write trace carries a follower span"
        t = stitched[0]
        fs = next(s for s in t.spans if s.name == "follower.apply")
        assert fs.proc == "follower"
        assert fs.parent_id == t.root.span_id
        assert fs.trace_id == t.trace_id
    finally:
        if fol is not None:
            fol.close()
        db.close()


def test_dropped_ack_degrades_to_primary_only(tmp_path):
    from toplingdb_tpu.env.fault_injection import ShipFaultInjector
    from toplingdb_tpu.replication.follower import FollowerDB
    from toplingdb_tpu.replication.log_shipper import (
        FaultyTransport, LocalTransport, LogShipper,
    )

    src = str(tmp_path / "db")
    db = DB.open(src, topts(statistics=Statistics()))
    fol = None
    try:
        ship = LogShipper(db)
        # Pull 0 delivers frames; pull 1 (the ack carrier) drops.
        inj = ShipFaultInjector(schedule={1: "drop"})
        fol = FollowerDB.open(src,
                              transport=FaultyTransport(
                                  LocalTransport(ship), inj),
                              mode="shared")
        db.put(b"dk1", b"dv1")
        assert fol.catch_up() > 0
        assert fol._span_outbox
        fol.catch_up()  # dropped: spans lost WITH the exchange
        assert not fol._span_outbox  # no leak: outbox cleared regardless
        writes = [t for t in db.tracer.finished(limit=100)
                  if t.name == "db.write"]
        assert writes
        assert all(
            all(s.name != "follower.apply" for s in t.spans)
            for t in writes), "dropped ack must leave primary-only traces"
        # later rounds keep working (no error latched anywhere)
        db.put(b"dk2", b"dv2")
        assert fol.catch_up() > 0
    finally:
        if fol is not None:
            fol.close()
        db.close()


# ---------------------------------------------------------------------------
# HTTP surface: /metrics gauges + parse, /traces, /stats_history
# ---------------------------------------------------------------------------

# name{labels} value  |  # comment — the Prometheus text shapes we emit.
_PROM_SAMPLE = re.compile(
    r"^[a-zA-Z_:][a-zA-Z0-9_:]*(\{[^{}]*\})? [-+0-9.eEinfa]+$")


def _parse_prometheus(text):
    samples = []
    for line in text.splitlines():
        if not line.strip():
            continue
        if line.startswith("#"):
            assert line.startswith(("# TYPE ", "# HELP ")), line
            continue
        assert _PROM_SAMPLE.match(line), f"bad exposition line: {line!r}"
        samples.append(line.split(" ")[0])
    return samples


def test_http_metrics_traces_stats_history(tmp_path):
    from toplingdb_tpu.utils.config import SidePluginRepo

    repo = SidePluginRepo()
    db = repo.open_db({"path": str(tmp_path / "db"),
                       "options": {"create_if_missing": True,
                                   "trace_sample_every": 1,
                                   "write_buffer_size": 1 << 20}},
                      name="main")
    port = repo.start_http()
    base = f"http://127.0.0.1:{port}"
    try:
        fill(db, 300)
        db.get(b"key000001")
        db.flush()
        db.persist_stats()

        # /metrics: parses as Prometheus text; counters AND gauges present
        with urllib.request.urlopen(f"{base}/metrics") as r:
            assert r.headers["Content-Type"].startswith("text/plain")
            text = r.read().decode()
        names = _parse_prometheus(text)
        joined = "\n".join(names)
        assert 'tpulsm_bytes_written{db="main"}' in joined
        assert 'tpulsm_level_files{db="main",level="0"}' in joined
        assert 'tpulsm_last_sequence{db="main"}' in joined
        assert "tpulsm_trace_ring_retained" in joined
        assert "tpulsm_db_write_micros_count" in joined

        # /traces/main: summaries; /traces/main/<id>: Chrome trace JSON
        with urllib.request.urlopen(f"{base}/traces/main") as r:
            body = json.loads(r.read())
        assert body["tracer"]["sample_every"] == 1
        assert body["traces"]
        tid = body["traces"][0]["trace_id"]
        with urllib.request.urlopen(f"{base}/traces/main/{tid}") as r:
            chrome = json.loads(r.read())
        assert chrome["traceEvents"]
        with urllib.request.urlopen(f"{base}/view/traces/main") as r:
            html = r.read().decode()
        assert "waterfall" in html or "traces: main" in html

        # /stats_history/main?window=
        with urllib.request.urlopen(
                f"{base}/stats_history/main?window=3600") as r:
            hist = json.loads(r.read())
        assert hist["n_samples"] >= 1
        assert any("number.keys.written" in s["tickers"]
                   for s in hist["samples"])
        with urllib.request.urlopen(
                f"{base}/stats_history/main?window=-1") as r:
            pass
    finally:
        repo.stop_http()
        db.close()


# ---------------------------------------------------------------------------
# PerfContext / IOStats satellites
# ---------------------------------------------------------------------------


def test_perfcontext_chunk_vs_per_entry_parity(tmp_path):
    """The scan plane's windowed tpulsm_scan_blocks reads must feed
    block_read_count/block_read_byte exactly like the per-entry path."""
    saved = os.environ.get("TPULSM_ITER_CHUNK")
    db = DB.open(str(tmp_path / "db"),
                 Options(create_if_missing=True,
                         write_buffer_size=32 << 10))
    try:
        import random

        rng = random.Random(3)
        for i in range(3000):
            db.put(b"key%06d" % rng.randrange(3000), b"v%06d" % i)
        db.flush()
        db.wait_for_compactions()

        def scan_counts(chunk):
            os.environ["TPULSM_ITER_CHUNK"] = chunk
            st.perf_level = 1
            st.perf_context().reset()
            it = db.new_iterator()
            it.seek_to_first()
            n = sum(1 for _ in it.entries())
            ctx = st.perf_context()
            st.perf_level = 0
            return n, ctx.block_read_count, ctx.block_read_byte

        n0, c0, b0 = scan_counts("0")
        n1, c1, b1 = scan_counts("1")
        assert n0 == n1 > 1000
        assert c0 == c1 > 0
        assert b0 == b1 > 0
    finally:
        st.perf_level = 0
        if saved is None:
            os.environ.pop("TPULSM_ITER_CHUNK", None)
        else:
            os.environ["TPULSM_ITER_CHUNK"] = saved
        db.close()


def test_iostats_context_fed_by_posix_env(tmp_path):
    st.perf_level = 2
    try:
        ctx = st.iostats_context()
        ctx.reset()
        db = DB.open(str(tmp_path / "db"), Options(create_if_missing=True))
        db.put(b"iok", b"iov" * 10, WriteOptions(sync=True))
        db.flush()
        db.close()
        assert ctx.bytes_written > 0
        assert ctx.fsync_nanos > 0
        ctx.reset()
        db = DB.open(str(tmp_path / "db"), Options(create_if_missing=False))
        db.close()
        assert ctx.bytes_read > 0  # recovery read the MANIFEST/WAL back
        d = ctx.to_dict()
        assert set(d) == {"bytes_written", "bytes_read", "write_nanos",
                          "read_nanos", "fsync_nanos"}
    finally:
        st.perf_level = 0


# ---------------------------------------------------------------------------
# Event log: trace correlation, stats_dump, ldb dump_events
# ---------------------------------------------------------------------------


def test_event_log_correlation_and_dump_events(tmp_path, capsys):
    from toplingdb_tpu.tools.ldb import main as ldb_main

    d = str(tmp_path / "db")
    db = DB.open(d, topts(statistics=Statistics()))
    t_mid = None
    try:
        fill(db, 50)
        db.flush()
        time.sleep(0.01)
        t_mid = time.time()
        time.sleep(0.01)
        db.put(b"late", b"entry")
        db.flush()
        # stats_dump line through the dump hook (thread path covered by
        # the scheduler's own loop; the hook is what the knob adds).
        db.persist_stats()
        db._log_stats_dump()
    finally:
        db.close()

    assert ldb_main(["--db", d, "dump_events"]) == 0
    out = capsys.readouterr().out
    events = [json.loads(l) for l in out.splitlines()
              if l.startswith("{")]
    kinds = {e["event"] for e in events}
    assert "flush_finished" in kinds
    assert "stats_dump" in kinds
    flushes = [e for e in events if e["event"] == "flush_finished"]
    assert any("trace_id" in e for e in flushes), \
        "flush events must correlate to their trace"
    # --since filters on time_micros
    assert ldb_main(["--db", d, f"--since={t_mid}", "dump_events"]) == 0
    out2 = capsys.readouterr().out
    later = [json.loads(l) for l in out2.splitlines() if l.startswith("{")]
    assert 0 < len(later) < len(events)
    assert all(e["time_micros"] >= int(t_mid * 1e6) for e in later)


def test_stats_dump_scheduler_thread(tmp_path):
    d = str(tmp_path / "db")
    db = DB.open(d, Options(create_if_missing=True,
                            statistics=Statistics(),
                            stats_dump_period_sec=1))
    try:
        fill(db, 50)
        deadline = time.time() + 5.0
        while time.time() < deadline:
            if db.stats_history.last_sample() is not None:
                break
            time.sleep(0.05)
        assert db.stats_history.last_sample() is not None
    finally:
        db.close()
    from toplingdb_tpu.env import default_env

    log = default_env().read_file(f"{d}/LOG").decode()
    assert '"event": "stats_dump"' in log


# ---------------------------------------------------------------------------
# check_telemetry lint
# ---------------------------------------------------------------------------


def test_check_telemetry_lint_clean():
    from toplingdb_tpu.tools import check_telemetry

    assert check_telemetry.run() == []


def test_check_telemetry_catches_forked_names(tmp_path):
    from toplingdb_tpu.tools import check_telemetry as ct

    bad = tmp_path / "bad.py"
    bad.write_text(
        "def f(stats, st):\n"
        "    stats.record_tick('no.such.ticker')\n"
        "    stats.record_in_histogram(st.NOT_A_REAL_CONSTANT, 1)\n"
        "    span('rogue.span.name')\n"
    )
    values, attrs = ct.declared_stat_names()
    names = ct.span_names_in_architecture(
        os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
    assert names  # the ARCHITECTURE table is discoverable
    assert "db.write" in names and "pipeline.scan" in names
    vio = ct.check_file(str(bad), values, attrs, names)
    assert len(vio) == 3
    assert any("no.such.ticker" in v for v in vio)
    assert any("NOT_A_REAL_CONSTANT" in v for v in vio)
    assert any("rogue.span.name" in v for v in vio)
