"""Utilities layer: checkpoint, backup, sst writer/ingest, TTL, WBWI,
transactions, statistics, listeners, config/registry, HTTP introspection."""

import json
import struct
import threading
import urllib.request

import pytest

from toplingdb_tpu.db.db import DB
from toplingdb_tpu.options import Options, ReadOptions, WriteOptions


def opts(**kw):
    kw.setdefault("write_buffer_size", 16 * 1024)
    return Options(**kw)


# -- checkpoint / backup ----------------------------------------------------


def test_checkpoint_is_openable(tmp_path):
    from toplingdb_tpu.utilities.checkpoint import create_checkpoint

    src = str(tmp_path / "src")
    dst = str(tmp_path / "ckpt")
    with DB.open(src, opts()) as db:
        for i in range(500):
            db.put(b"k%04d" % i, b"v%04d" % i)
        create_checkpoint(db, dst)
        db.put(b"after", b"x")  # not in checkpoint
    with DB.open(dst, opts()) as db2:
        assert db2.get(b"k0123") == b"v0123"
        assert db2.get(b"after") is None


def test_backup_restore_and_purge(tmp_path):
    from toplingdb_tpu.utilities.backup_engine import BackupEngine

    src = str(tmp_path / "src")
    be = BackupEngine(str(tmp_path / "backups"))
    with DB.open(src, opts()) as db:
        db.put(b"a", b"1")
        b1 = be.create_backup(db)
        db.put(b"b", b"2")
        b2 = be.create_backup(db)
    infos = be.get_backup_info()
    assert [i["backup_id"] for i in infos] == [b1, b2]
    restored = str(tmp_path / "restored")
    be.restore_db_from_backup(b1, restored)
    with DB.open(restored, opts()) as db2:
        assert db2.get(b"a") == b"1"
        assert db2.get(b"b") is None
    be.purge_old_backups(1)
    assert [i["backup_id"] for i in be.get_backup_info()] == [b2]


# -- sst file writer / ingestion -------------------------------------------


def test_sst_file_writer_and_ingest(tmp_path):
    from toplingdb_tpu.utilities.sst_file_writer import (
        SstFileReader, SstFileWriter, ingest_external_file,
    )

    ext = str(tmp_path / "ext.sst")
    w = SstFileWriter()
    w.open(ext)
    for i in range(100):
        w.put(b"ing%04d" % i, b"x%04d" % i)
    w.finish()

    r = SstFileReader(ext)
    assert r.properties.num_entries == 100

    dbdir = str(tmp_path / "db")
    with DB.open(dbdir, opts()) as db:
        db.put(b"existing", b"1")
        snap = db.get_snapshot()
        level = ingest_external_file(db, ext)
        assert db.get(b"ing0050") == b"x0050"
        assert db.get(b"existing") == b"1"
        # Snapshot taken before ingestion must not see ingested keys.
        assert db.get(b"ing0050", ReadOptions(snapshot=snap)) is None
        snap.release()
    with DB.open(dbdir, opts()) as db:
        assert db.get(b"ing0099") == b"x0099"


def test_sst_writer_rejects_out_of_order(tmp_path):
    from toplingdb_tpu.utilities.sst_file_writer import SstFileWriter
    from toplingdb_tpu.utils.status import InvalidArgument

    w = SstFileWriter()
    w.open(str(tmp_path / "x.sst"))
    w.put(b"b", b"1")
    with pytest.raises(InvalidArgument):
        w.put(b"a", b"2")


# -- TTL --------------------------------------------------------------------


def test_ttl_db(tmp_path):
    from toplingdb_tpu.utilities.ttl import TtlDB

    clock = [1000.0]
    with TtlDB.open(str(tmp_path / "db"), ttl=100, options=opts(),
                    clock=lambda: clock[0]) as db:
        db.put(b"k", b"v")
        assert db.get(b"k") == b"v"
        clock[0] += 200  # expire
        assert db.get(b"k") is None
        db.flush()
        db.compact_range()  # filter physically drops it
        v = db.db.versions.current
        assert sum(f.num_entries for _, f in v.all_files()) == 0


# -- WriteBatchWithIndex ----------------------------------------------------


def test_wbwi_read_your_writes(tmp_path):
    from toplingdb_tpu.utilities.write_batch_with_index import WriteBatchWithIndex

    with DB.open(str(tmp_path / "db"), opts()) as db:
        db.put(b"base", b"db-val")
        db.put(b"gone", b"x")
        w = WriteBatchWithIndex()
        w.put(b"new", b"batch-val")
        w.delete(b"gone")
        w.put(b"base", b"overridden")
        assert w.get_from_batch_and_db(db, b"new") == b"batch-val"
        assert w.get_from_batch_and_db(db, b"gone") is None
        assert w.get_from_batch_and_db(db, b"base") == b"overridden"
        assert w.get_from_batch_and_db(db, b"missing") is None
        # Commit applies atomically.
        db.write(w.batch)
        assert db.get(b"base") == b"overridden"
        assert db.get(b"gone") is None


def test_wbwi_merge_with_db_base(tmp_path):
    from toplingdb_tpu.utilities.write_batch_with_index import WriteBatchWithIndex
    from toplingdb_tpu.utils.merge_operator import UInt64AddOperator

    op = UInt64AddOperator()
    with DB.open(str(tmp_path / "db"), opts(merge_operator=op)) as db:
        db.put(b"c", struct.pack("<Q", 10))
        w = WriteBatchWithIndex(op)
        w.merge(b"c", struct.pack("<Q", 5))
        assert struct.unpack("<Q", w.get_from_batch_and_db(db, b"c"))[0] == 15


def test_wbwi_iterator_with_base(tmp_path):
    from toplingdb_tpu.utilities.write_batch_with_index import WriteBatchWithIndex

    with DB.open(str(tmp_path / "db"), opts()) as db:
        db.put(b"a", b"1")
        db.put(b"c", b"3")
        w = WriteBatchWithIndex()
        w.put(b"b", b"2")
        w.delete(b"c")
        w.put(b"d", b"4")
        merged = w.iterator_with_base(db)
        assert merged == [(b"a", b"1"), (b"b", b"2"), (b"d", b"4")]


# -- transactions -----------------------------------------------------------


def test_pessimistic_transaction_commit_rollback(tmp_path):
    from toplingdb_tpu.utilities.transactions import TransactionDB

    with TransactionDB.open(str(tmp_path / "db"), opts()) as tdb:
        txn = tdb.begin_transaction()
        txn.put(b"k", b"v1")
        assert txn.get(b"k") == b"v1"          # read your writes
        assert tdb.get(b"k") is None           # not visible before commit
        txn.commit()
        assert tdb.get(b"k") == b"v1"

        txn2 = tdb.begin_transaction()
        txn2.put(b"k", b"v2")
        txn2.rollback()
        assert tdb.get(b"k") == b"v1"


def test_pessimistic_lock_conflict(tmp_path):
    from toplingdb_tpu.utilities.transactions import TransactionDB
    from toplingdb_tpu.utils.status import Busy

    with TransactionDB.open(str(tmp_path / "db"), opts()) as tdb:
        t1 = tdb.begin_transaction(lock_timeout=0.1)
        t2 = tdb.begin_transaction(lock_timeout=0.1)
        t1.put(b"k", b"t1")
        with pytest.raises(Busy):
            t2.put(b"k", b"t2")
        t1.commit()
        t2.put(b"k", b"t2")  # lock now free
        t2.commit()
        assert tdb.get(b"k") == b"t2"


def test_deadlock_detection(tmp_path):
    from toplingdb_tpu.utilities.transactions import DeadlockError, TransactionDB
    from toplingdb_tpu.utils.status import Busy

    with TransactionDB.open(str(tmp_path / "db"), opts()) as tdb:
        t1 = tdb.begin_transaction(lock_timeout=5.0)
        t2 = tdb.begin_transaction(lock_timeout=5.0)
        t1.put(b"a", b"1")
        t2.put(b"b", b"2")
        errors = []

        def t1_waits():
            try:
                t1.put(b"b", b"1b")  # blocks on t2
            except Busy as e:
                errors.append(e)

        th = threading.Thread(target=t1_waits)
        th.start()
        import time

        time.sleep(0.1)
        with pytest.raises(Busy):  # DeadlockError is a Busy
            t2.put(b"a", b"2a")
        t2.rollback()
        th.join()
        t1.commit()


def test_get_for_update_blocks_writers(tmp_path):
    from toplingdb_tpu.utilities.transactions import TransactionDB
    from toplingdb_tpu.utils.status import Busy

    with TransactionDB.open(str(tmp_path / "db"), opts()) as tdb:
        tdb.put(b"k", b"v0")
        t1 = tdb.begin_transaction(lock_timeout=0.1)
        assert t1.get_for_update(b"k") == b"v0"
        t2 = tdb.begin_transaction(lock_timeout=0.1)
        with pytest.raises(Busy):
            t2.put(b"k", b"nope")
        t1.commit()


def test_optimistic_transaction_conflict(tmp_path):
    from toplingdb_tpu.utilities.transactions import OptimisticTransactionDB
    from toplingdb_tpu.utils.status import Busy

    with OptimisticTransactionDB.open(str(tmp_path / "db"), opts()) as odb:
        odb.db.put(b"k", b"v0")
        t1 = odb.begin_transaction()
        t2 = odb.begin_transaction()
        assert t1.get_for_update(b"k") == b"v0"
        t2.put(b"k", b"t2")
        t2.commit()
        t1.put(b"k", b"t1")
        with pytest.raises(Busy):
            t1.commit()
        assert odb.get(b"k") == b"t2"


def test_optimistic_no_conflict(tmp_path):
    from toplingdb_tpu.utilities.transactions import OptimisticTransactionDB

    with OptimisticTransactionDB.open(str(tmp_path / "db"), opts()) as odb:
        t1 = odb.begin_transaction()
        t1.put(b"x", b"1")
        t1.commit()
        assert odb.get(b"x") == b"1"


# -- statistics / listeners -------------------------------------------------


def test_statistics_collected(tmp_path):
    from toplingdb_tpu.utils import statistics as st

    stats = st.Statistics()
    with DB.open(str(tmp_path / "db"), opts(statistics=stats)) as db:
        for i in range(2000):
            db.put(b"k%05d" % i, b"v" * 50)
        db.flush()
        db.compact_range()
        assert stats.get_ticker_count(st.NUMBER_KEYS_WRITTEN) == 2000
        assert stats.get_ticker_count(st.FLUSH_WRITE_BYTES) > 0
        assert stats.get_ticker_count(st.COMPACT_READ_BYTES) > 0
        assert stats.get_ticker_count(st.LCOMPACTION_READ_BYTES) > 0
        h = stats.get_histogram(st.COMPACTION_TIME_MICROS)
        assert h.count >= 1
        assert "COUNT" in stats.to_string()


def test_listener_callbacks(tmp_path):
    from toplingdb_tpu.utils.listener import EventListener

    events = []

    class L(EventListener):
        def on_flush_completed(self, db, info):
            events.append(("flush", info.file_number))

        def on_compaction_completed(self, db, info):
            events.append(("compaction", info.input_level, info.output_level))

    with DB.open(str(tmp_path / "db"), opts(listeners=[L()])) as db:
        for i in range(100):
            db.put(b"k%03d" % i, b"v")
        db.flush()
        db.compact_range()
    kinds = {e[0] for e in events}
    assert "flush" in kinds and "compaction" in kinds


def test_event_log_written(tmp_path):
    dbdir = str(tmp_path / "db")
    with DB.open(dbdir, opts()) as db:
        db.put(b"a", b"1")
        db.flush()
    lines = open(dbdir + "/LOG").read().strip().splitlines()
    evs = [json.loads(l)["event"] for l in lines]
    assert "flush_finished" in evs


# -- config / registry / HTTP -----------------------------------------------


def test_options_from_config_and_repo(tmp_path):
    from toplingdb_tpu.utils.config import SidePluginRepo

    repo = SidePluginRepo()
    cfg = {
        "path": str(tmp_path / "db"),
        "options": {
            "write_buffer_size": 32768,
            "compaction_style": "leveled",
            "merge_operator": "uint64add",
            "statistics": "default",
            "table_options": {"block_size": 2048,
                              "filter_policy": {"class": "bloom",
                                                "params": {"bits_per_key": 12}}},
        },
    }
    db = repo.open_db(cfg, name="testdb")
    db.merge(b"c", struct.pack("<Q", 4))
    db.merge(b"c", struct.pack("<Q", 6))
    assert struct.unpack("<Q", db.get(b"c"))[0] == 10
    assert db.options.write_buffer_size == 32768
    assert db.options.table_options.block_size == 2048

    port = repo.start_http()
    def fetch(path):
        with urllib.request.urlopen(f"http://127.0.0.1:{port}{path}") as r:
            return json.loads(r.read())
    assert fetch("/dbs") == {"dbs": ["testdb"]}
    assert "levelstats" in fetch("/stats/testdb")
    assert fetch("/config/testdb")["path"] == cfg["path"]
    repo.close_all()


def test_config_rejects_unknown_option():
    from toplingdb_tpu.utils.config import options_from_config
    from toplingdb_tpu.utils.status import InvalidArgument

    with pytest.raises(InvalidArgument):
        options_from_config({"no_such_option": 1})


# -- tools ------------------------------------------------------------------


def test_db_bench_cli(tmp_path, capsys):
    from toplingdb_tpu.tools.db_bench import main

    main([
        "--benchmarks=fillseq,readseq,readrandom,compact,stats",
        "--num=500", f"--db={tmp_path}/bench",
    ])
    out = capsys.readouterr().out
    assert "fillseq" in out and "ops/sec" in out


def test_sst_dump_cli(tmp_path, capsys):
    from toplingdb_tpu.tools.sst_dump import main as sst_main

    dbdir = str(tmp_path / "db")
    with DB.open(dbdir, opts()) as db:
        for i in range(50):
            db.put(b"k%03d" % i, b"v%03d" % i)
        db.flush()
        files = [f for _, f in db.versions.current.all_files()]
        path = f"{dbdir}/{files[0].number:06d}.sst"
    assert sst_main([f"--file={path}", "--command=verify"]) == 0
    assert sst_main([f"--file={path}", "--command=props"]) == 0
    out = capsys.readouterr().out
    assert "num_entries: 50" in out


def test_ldb_cli(tmp_path, capsys):
    from toplingdb_tpu.tools.ldb import main as ldb_main

    dbdir = str(tmp_path / "db")
    assert ldb_main([f"--db={dbdir}", "put", "alpha", "1"]) == 0
    assert ldb_main([f"--db={dbdir}", "get", "alpha"]) == 0
    assert ldb_main([f"--db={dbdir}", "scan"]) == 0
    assert ldb_main([f"--db={dbdir}", "manifest_dump"]) == 0
    out = capsys.readouterr().out
    assert "alpha" in out
    assert ldb_main([f"--db={dbdir}", "get", "missing"]) == 1


# -- read-only / secondary --------------------------------------------------


def test_readonly_db(tmp_path):
    from toplingdb_tpu.db.db_readonly import ReadOnlyDB
    from toplingdb_tpu.utils.status import NotSupported

    src = str(tmp_path / "db")
    with DB.open(src, opts()) as db:
        for i in range(100):
            db.put(b"k%03d" % i, b"v%03d" % i)
        db.flush()
        db.put(b"unflushed", b"wal-only")
    ro = ReadOnlyDB.open(src)
    assert ro.get(b"k050") == b"v050"
    assert ro.get(b"unflushed") == b"wal-only"  # WAL replayed read-only
    with pytest.raises(NotSupported):
        ro.put(b"x", b"y")
    ro.close()
    # Primary can still open normally afterward.
    with DB.open(src, opts()) as db:
        assert db.get(b"k050") == b"v050"


def test_secondary_catches_up(tmp_path):
    from toplingdb_tpu.db.db_readonly import SecondaryDB

    src = str(tmp_path / "db")
    db = DB.open(src, opts())
    db.put(b"a", b"1")
    db.flush()
    sec = SecondaryDB.open(src)
    assert sec.get(b"a") == b"1"
    db.put(b"b", b"2")
    db.flush()
    sec.try_catch_up_with_primary()
    assert sec.get(b"b") == b"2"
    sec.close()
    db.close()


# -- trace / replay ---------------------------------------------------------


def test_trace_replay_analyze(tmp_path):
    from toplingdb_tpu.utils.trace import Replayer, Tracer, analyze_trace

    src = str(tmp_path / "db")
    trace = str(tmp_path / "trace.bin")
    with DB.open(src, opts()) as db:
        t = Tracer(db, trace)
        t.put(b"a", b"1")
        t.put(b"b", b"2")
        t.get(b"a")
        t.delete(b"b")
        t.close()
    dst = str(tmp_path / "replayed")
    with DB.open(dst, opts()) as db2:
        n = Replayer(db2, trace).replay()
        assert n == 4
        assert db2.get(b"a") == b"1"
        assert db2.get(b"b") is None
        stats = analyze_trace(db2.env, trace)
        assert stats["total_ops"] == 4
        assert stats["per_op"]["put"] == 2


# -- cache / rate limiter / write buffer manager -----------------------------


def test_lru_cache_and_block_cache_integration(tmp_path):
    from toplingdb_tpu.utils.cache import LRUCache
    from toplingdb_tpu.db.table_cache import TableCache
    from toplingdb_tpu.db.dbformat import InternalKeyComparator

    cache = LRUCache(1 << 20)
    src = str(tmp_path / "db")
    with DB.open(src, opts()) as db:
        for i in range(500):
            db.put(b"k%04d" % i, b"v" * 100)
        db.flush()
        files = [f for _, f in db.versions.current.all_files()]
    icmp = InternalKeyComparator()
    from toplingdb_tpu.env import default_env

    tc = TableCache(default_env(), src, icmp, block_cache=cache)
    r = tc.get_reader(files[0].number)
    it = r.new_iterator(); it.seek_to_first()
    sum(1 for _ in it.entries())
    it2 = r.new_iterator(); it2.seek_to_first()
    sum(1 for _ in it2.entries())
    assert cache.usage() > 0
    assert cache.hit_rate() > 0.3


def test_rate_limiter_enforces_rate():
    import time

    from toplingdb_tpu.utils.rate_limiter import RateLimiter

    rl = RateLimiter(1_000_000)  # 1 MB/s
    t0 = time.monotonic()
    for _ in range(5):
        rl.request(100_000)  # 500 KB total
    dt = time.monotonic() - t0
    assert rl.total_through == 500_000
    assert dt >= 0.25  # at 1MB/s, 500KB needs >= ~0.4s with initial burst


def test_write_buffer_manager():
    from toplingdb_tpu.utils.rate_limiter import WriteBufferManager

    m = WriteBufferManager(1000)
    m.reserve(600)
    assert not m.should_flush()
    m.reserve(600)
    assert m.should_flush()
    m.free(900)
    assert not m.should_flush()


# -- fault injection --------------------------------------------------------


def test_fault_injection_env(tmp_path):
    from toplingdb_tpu.env.fault_injection import FaultInjectionEnv
    from toplingdb_tpu.env import PosixEnv
    from toplingdb_tpu.utils.status import Status

    fenv = FaultInjectionEnv(PosixEnv())
    src = str(tmp_path / "db")
    db = DB.open(src, opts(), env=fenv)
    db.put(b"synced", b"1", WriteOptions(sync=True))
    db.put(b"unsynced", b"2")
    fenv.drop_unsynced_and_deactivate()
    with pytest.raises(Status):
        db.put(b"x", b"y", WriteOptions(sync=True))
    db._closed = True  # simulate crash (no clean close)
    fenv.reactivate_and_truncate()
    db2 = DB.open(src, opts(), env=fenv)
    assert db2.get(b"synced") == b"1"
    assert db2.get(b"unsynced") is None  # lost with the crash
    db2.close()
    assert fenv.io_counts.get("append", 0) > 0


# -- stress tool ------------------------------------------------------------


def test_db_stress_small(tmp_path):
    from toplingdb_tpu.tools.db_stress import main as stress_main

    rc = stress_main([
        f"--db={tmp_path}/sdb", "--ops=1500", "--threads=3", "--max-key=200",
    ])
    assert rc == 0
    # Second run verifies persisted expected state against the reopened DB.
    rc = stress_main([
        f"--db={tmp_path}/sdb", "--ops=500", "--threads=2", "--max-key=200",
    ])
    assert rc == 0


# -- review regressions -----------------------------------------------------


def test_backup_purge_with_double_digit_ids(tmp_path):
    """Review regression: purge must drop the numerically oldest backups,
    not the lexicographically smallest filenames."""
    from toplingdb_tpu.utilities.backup_engine import BackupEngine

    src = str(tmp_path / "src")
    be = BackupEngine(str(tmp_path / "backups"))
    with DB.open(src, opts()) as db:
        ids = []
        for i in range(11):
            db.put(b"k%02d" % i, b"v")
            ids.append(be.create_backup(db))
    be.purge_old_backups(2)
    kept = [i["backup_id"] for i in be.get_backup_info()]
    assert kept == ids[-2:]  # the NEWEST two survive
    restored = str(tmp_path / "restored")
    be.restore_db_from_backup(kept[-1], restored)
    with DB.open(restored, opts()) as db2:
        assert db2.get(b"k10") == b"v"


def test_optimistic_conflict_between_snapshot_and_track(tmp_path):
    """Review regression: a write landing between txn snapshot and
    get_for_update must still be detected as a conflict."""
    from toplingdb_tpu.utilities.transactions import OptimisticTransactionDB
    from toplingdb_tpu.utils.status import Busy

    with OptimisticTransactionDB.open(str(tmp_path / "db"), opts()) as odb:
        odb.db.put(b"k", b"v0")
        t1 = odb.begin_transaction()       # snapshot here
        odb.db.put(b"k", b"v1")            # interleaved write
        assert t1.get_for_update(b"k") == b"v0"  # reads at snapshot
        t1.put(b"k", b"t1")
        with pytest.raises(Busy):
            t1.commit()                     # lost update prevented
        assert odb.get(b"k") == b"v1"


def test_checkpoint_on_mem_env():
    """Review regression: checkpoint must work through a non-posix Env."""
    from toplingdb_tpu.env import MemEnv
    from toplingdb_tpu.utilities.checkpoint import create_checkpoint

    env = MemEnv()
    db = DB.open("/db", opts(), env=env)
    for i in range(50):
        db.put(b"k%02d" % i, b"v%02d" % i)
    create_checkpoint(db, "/ckpt")
    db.close()
    db2 = DB.open("/ckpt", opts(), env=env)
    assert db2.get(b"k25") == b"v25"
    db2.close()


def test_rate_limiter_oversized_request():
    """Review regression: requests larger than one refill period must still
    be throttled (split into chunks)."""
    import time

    from toplingdb_tpu.utils.rate_limiter import RateLimiter

    rl = RateLimiter(1_000_000, refill_period_us=50_000)  # 50KB/period
    t0 = time.monotonic()
    rl.request(500_000)  # 10 periods worth
    dt = time.monotonic() - t0
    assert dt >= 0.3


# -- sync points / wide columns ---------------------------------------------


def test_sync_point_callbacks_and_dependencies(tmp_path):
    from toplingdb_tpu.utils.sync_point import get_sync_point_registry

    reg = get_sync_point_registry()
    seen = []
    try:
        reg.set_callback("FlushJob::Start", lambda arg: seen.append("flush"))
        reg.enable_processing()
        with DB.open(str(tmp_path / "db"), opts()) as db:
            db.put(b"k", b"v")
            db.flush()
        assert "flush" in seen
    finally:
        reg.clear_all()


def test_wide_columns(tmp_path):
    from toplingdb_tpu.db.wide_columns import (
        DEFAULT_COLUMN, decode_entity, get_entity, put_entity,
    )

    with DB.open(str(tmp_path / "db"), opts()) as db:
        put_entity(db, b"user1", {b"name": b"ada", b"age": b"36"})
        db.put(b"plain", b"simple-value")
        e = get_entity(db, b"user1")
        assert e == {b"name": b"ada", b"age": b"36"}
        # Plain values present as the default column.
        assert get_entity(db, b"plain") == {DEFAULT_COLUMN: b"simple-value"}
        assert get_entity(db, b"missing") is None
        db.flush()
        db.compact_range()
        assert get_entity(db, b"user1")[b"name"] == b"ada"


def test_compact_on_deletion_collector(tmp_db_path):
    """Collector marks tombstone-dense files; the picker prioritizes them
    (reference compact_on_deletion_collector.cc)."""
    from toplingdb_tpu.db.db import DB
    from toplingdb_tpu.options import Options
    from toplingdb_tpu.utils.table_properties_collector import (
        CompactOnDeletionCollectorFactory,
    )

    o = Options(disable_auto_compactions=True)
    o.table_options.properties_collector_factories = [
        CompactOnDeletionCollectorFactory(window_size=16, deletion_trigger=8)
    ]
    with DB.open(tmp_db_path, o) as db:
        for i in range(100):
            db.put(b"k%03d" % i, b"v")
        for i in range(40, 60):
            db.delete(b"k%03d" % i)
        db.flush()
        f = db.versions.current.files[0][0]
        assert f.marked_for_compaction, "dense deletions must mark the file"
        # Sparse deletions (below the window trigger) must NOT mark —
        # asserted in the SAME session the collector ran in.
        for i in range(100):
            db.put(b"s%03d" % i, b"v")
        db.delete(b"s050")
        db.flush()
        newest = max((f for lvl in db.versions.current.files for f in lvl),
                     key=lambda f: f.number)
        assert not newest.marked_for_compaction
    with DB.open(tmp_db_path, Options(disable_auto_compactions=True)) as db:
        # The mark survives reopen (persisted via the extended NEW_FILE tag).
        assert any(f.marked_for_compaction
                   for lvl in db.versions.current.files for f in lvl)


def test_user_collected_properties_in_sst(tmp_db_path):
    from toplingdb_tpu.db.db import DB
    from toplingdb_tpu.options import Options
    from toplingdb_tpu.utils.table_properties_collector import (
        TablePropertiesCollector, TablePropertiesCollectorFactory,
    )

    class Counting(TablePropertiesCollector):
        def __init__(self):
            self.n = 0

        def name(self):
            return "Counting"

        def add_user_key(self, key, value, entry_type, seq, file_size):
            self.n += 1

        def finish(self):
            return {"counting.n": str(self.n).encode()}

    class F(TablePropertiesCollectorFactory):
        def name(self):
            return "CountingFactory"

        def create(self):
            return Counting()

    o = Options(disable_auto_compactions=True)
    o.table_options.properties_collector_factories = [F()]
    with DB.open(tmp_db_path, o) as db:
        for i in range(25):
            db.put(b"k%02d" % i, b"v")
        db.flush()
        f = db.versions.current.files[0][0]
        r = db.table_cache.get_reader(f.number)
        assert r.properties.user_collected["counting.n"] == b"25"


def test_new_merge_operators():
    import struct

    from toplingdb_tpu.utils.merge_operator import (
        AggMergeOperator, BytesXOROperator, CassandraValueMergeOperator,
        SortListOperator, create_merge_operator,
    )

    x = BytesXOROperator()
    assert x.full_merge(b"k", b"\x0f\x0f", [b"\xff"]) == b"\xf0\x0f"
    assert x.partial_merge(b"k", b"\x01", b"\x01") == b"\x00"

    s = SortListOperator()
    assert s.full_merge(b"k", b"5,1", [b"3", b"2,4"]) == b"1,2,3,4,5"

    a = AggMergeOperator()
    packed = a.full_merge(b"k", a.pack(b"sum", struct.pack("<Q", 10)),
                          [a.pack(b"sum", struct.pack("<Q", 5)),
                           a.pack(b"sum", struct.pack("<Q", 7))])
    assert struct.unpack("<Q", a._unpack(packed)[1])[0] == 22
    last = a.full_merge(b"k", None, [a.pack(b"last", b"A"),
                                     a.pack(b"last", b"B")])
    assert a._unpack(last)[1] == b"B"

    c = CassandraValueMergeOperator()
    from toplingdb_tpu.utils import coding

    def row(cid, ts, val):
        return (coding.encode_varint32(cid) + struct.pack("<Q", ts)
                + coding.encode_varint32(len(val)) + val)

    merged = c.full_merge(b"k", row(1, 100, b"old") + row(2, 50, b"keep"),
                          [row(1, 200, b"new")])
    cols = c._cols(merged)
    assert cols[1] == (200, b"new") and cols[2] == (50, b"keep")

    for name in ("bytesxor", "sortlist", "aggmerge", "cassandra",
                 "CassandraValueMergeOperator"):
        assert create_merge_operator(name) is not None


def test_stats_history_and_seqno_time(tmp_db_path):
    from toplingdb_tpu.db.db import DB
    from toplingdb_tpu.options import Options
    from toplingdb_tpu.utils.statistics import Statistics

    o = Options(statistics=Statistics(), seqno_time_sample_period_sec=0)
    with DB.open(tmp_db_path, o) as db:
        db.put(b"a", b"1")
        db.persist_stats()
        db.put(b"b", b"2")
        db.put(b"c", b"3")
        db.persist_stats()
        hist = db.get_stats_history()
        assert len(hist) == 2
        from toplingdb_tpu.utils import statistics as st

        # Second sample holds only the delta (2 keys) since the first.
        assert hist[1][1].get(st.NUMBER_KEYS_WRITTEN) == 2
        # Period 0 = MANUAL sampling only (consistent with
        # stats_persist_period_sec); automatic samples are off.
        assert len(db.seqno_to_time) == 0
        db.seqno_to_time.append(db.versions.last_sequence, 12345)
        t = db.seqno_to_time.get_proximal_time(db.versions.last_sequence)
        assert t == 12345
        assert db.seqno_to_time.get_proximal_seqno(2 ** 40) is not None


def test_seqno_to_time_mapping_unit():
    from toplingdb_tpu.utils.seqno_to_time import SeqnoToTimeMapping

    m = SeqnoToTimeMapping(max_capacity=4)
    for i in range(1, 11):
        m.append(i * 10, 1000 + i)
    assert len(m) <= 5
    assert m.get_proximal_time(5) is None       # predates mapping
    assert m.get_proximal_time(100) == 1010     # newest pair kept
    assert m.get_proximal_seqno(999) is None


def test_persistent_cache_spill_and_restart(tmp_path):
    """Evicted LRU blocks spill to the persistent tier, misses promote back,
    and the on-disk index survives a restart (reference
    utilities/persistent_cache + SecondaryCache promotion)."""
    from toplingdb_tpu.utils.cache import LRUCache
    from toplingdb_tpu.utils.persistent_cache import PersistentCache

    pdir = str(tmp_path / "pcache")
    sec = PersistentCache(pdir, capacity_bytes=1 << 20, file_size=8 * 1024)
    lru = LRUCache(4 * 1024, num_shards=1, secondary=sec)
    blocks = {b"blk%03d" % i: bytes([i % 256]) * 512 for i in range(32)}
    for k, v in blocks.items():
        lru.insert(k, v, len(v))
    # Early blocks were evicted from the 4KiB primary — must hit via disk.
    assert lru.lookup(b"blk000") == blocks[b"blk000"]
    assert sec.hits >= 1
    # Promotion: now resident in primary (no new secondary hit needed).
    h = sec.hits
    assert lru.lookup(b"blk000") == blocks[b"blk000"]
    assert sec.hits == h
    sec.close()
    # Restart: index rebuilt from the cache files.
    sec2 = PersistentCache(pdir, capacity_bytes=1 << 20, file_size=8 * 1024)
    assert sec2.lookup(b"blk005") == blocks[b"blk005"]
    sec2.close()


def test_persistent_cache_capacity_eviction(tmp_path):
    import os

    from toplingdb_tpu.utils.persistent_cache import PersistentCache

    pdir = str(tmp_path / "pc2")
    # Sync + uncompressed: this test pins the file-granularity EVICTION
    # mechanics (write-behind/compression have their own tests).
    pc = PersistentCache(pdir, capacity_bytes=32 * 1024, file_size=8 * 1024,
                         compress=False, write_behind=False)
    for i in range(200):
        pc.insert(b"k%04d" % i, b"x" * 500)
    assert pc.usage() <= 40 * 1024  # capacity + one in-flight file
    assert pc.lookup(b"k0199") is not None  # newest kept
    assert pc.lookup(b"k0000") is None      # oldest file dropped
    assert len(os.listdir(pdir)) <= 6
    pc.close()


def test_persistent_cache_ignores_corrupt_tail(tmp_path):
    import os

    from toplingdb_tpu.utils.persistent_cache import PersistentCache

    pdir = str(tmp_path / "pc3")
    pc = PersistentCache(pdir, capacity_bytes=1 << 20, compress=False,
                         write_behind=False)
    pc.insert(b"good", b"G" * 100)
    pc.insert(b"torn", b"T" * 100)
    pc.close()
    f = sorted(os.listdir(pdir))[0]
    path = os.path.join(pdir, f)
    data = open(path, "rb").read()
    open(path, "wb").write(data[:-30])  # tear the last record
    pc2 = PersistentCache(pdir, capacity_bytes=1 << 20)
    assert pc2.lookup(b"good") == b"G" * 100
    assert pc2.lookup(b"torn") is None
    pc2.close()


def test_persistent_cache_insert_after_close_is_inert(tmp_path):
    """insert()/_write_record after close() must early-return — a straggler
    (e.g. a reader promoting a block during DB shutdown) must not roll a
    FRESH cache file and resurrect the tier (ADVICE r5)."""
    import os

    from toplingdb_tpu.utils.persistent_cache import PersistentCache

    for wb in (False, True):
        pdir = str(tmp_path / f"pc_closed_{wb}")
        pc = PersistentCache(pdir, capacity_bytes=1 << 20, compress=False,
                             write_behind=wb)
        pc.insert(b"live", b"L" * 64)
        pc.flush()
        pc.close()
        files_after_close = sorted(os.listdir(pdir))
        pc.insert(b"straggler", b"S" * 64)
        pc._write_record(b"direct", b"D" * 64)
        pc.flush()
        assert sorted(os.listdir(pdir)) == files_after_close
        assert pc.lookup(b"straggler") is None
        # The pre-close insert is still on disk for the next incarnation.
        pc2 = PersistentCache(pdir, capacity_bytes=1 << 20)
        assert pc2.lookup(b"live") == b"L" * 64
        assert pc2.lookup(b"direct") is None
        pc2.close()


def test_persistent_cache_write_behind_and_compression(tmp_path):
    """The writeback thread drains the insert queue; compressed records
    round-trip; pending entries are visible to lookups immediately."""
    from toplingdb_tpu.utils.persistent_cache import PersistentCache

    pdir = str(tmp_path / "pc4")
    pc = PersistentCache(pdir, capacity_bytes=1 << 20, compress=True,
                         write_behind=True)
    val = b"compress-me " * 100
    for i in range(50):
        pc.insert(b"wb%03d" % i, val)
    # Visible BEFORE the writeback lands (pending-queue hit).
    assert pc.lookup(b"wb000") == val
    pc.flush()
    st = pc.stats()
    assert st["pending_bytes"] == 0 and st["inserts"] == 50
    if st["compressed"]:
        # 50 x 1.2KB highly-compressible records must land well under raw.
        assert st["bytes_written"] < 50 * len(val) // 2
    assert pc.lookup(b"wb042") == val
    pc.close()
    # Compressed records survive restart.
    pc2 = PersistentCache(pdir, capacity_bytes=1 << 20)
    assert pc2.lookup(b"wb042") == val
    pc2.close()


def test_persistent_cache_access_lru_eviction(tmp_path):
    """Eviction drops the least-recently-ACCESSED file, not the oldest:
    keys in the oldest file stay alive while they keep getting hit."""
    from toplingdb_tpu.utils.persistent_cache import PersistentCache

    pc = PersistentCache(str(tmp_path / "pc5"), capacity_bytes=24 * 1024,
                         file_size=8 * 1024, compress=False,
                         write_behind=False)
    # File 0 fills with hot keys; keep touching one of them as later
    # files push usage past capacity.
    for i in range(14):
        pc.insert(b"hot%03d" % i, b"h" * 500)
    assert pc.lookup(b"hot000") is not None
    for i in range(80):
        pc.insert(b"cold%03d" % i, b"c" * 500)
        pc.lookup(b"hot000")  # keep file 0 recent
    assert pc.lookup(b"hot000") is not None, "hot file evicted despite use"
    pc.close()


def test_persistent_cache_stats_surface(tmp_path):
    from toplingdb_tpu.utils.persistent_cache import PersistentCache

    pc = PersistentCache(str(tmp_path / "pc6"), capacity_bytes=1 << 20,
                         write_behind=False)
    pc.insert(b"a", b"x" * 200)
    assert pc.lookup(b"a") is not None
    assert pc.lookup(b"zz") is None
    st = pc.stats()
    assert st["hits"] == 1 and st["misses"] == 1
    assert 0 < st["hit_rate"] < 1
    assert st["bytes_written"] > 0 and st["files"] >= 1
    pc.close()


def test_db_with_block_cache_and_persistent_tier(tmp_db_path, tmp_path):
    from toplingdb_tpu.db.db import DB
    from toplingdb_tpu.options import Options
    from toplingdb_tpu.utils.cache import LRUCache
    from toplingdb_tpu.utils.persistent_cache import PersistentCache

    sec = PersistentCache(str(tmp_path / "pc"), capacity_bytes=1 << 20)
    o = Options(disable_auto_compactions=True,
                block_cache=LRUCache(8 * 1024, secondary=sec))
    with DB.open(tmp_db_path, o) as db:
        for i in range(2000):
            db.put(b"key%05d" % i, b"v%05d" % i)
        db.flush()
        for i in range(0, 2000, 17):
            assert db.get(b"key%05d" % i) == b"v%05d" % i
    sec.close()


def test_options_persistence_round_trip(tmp_db_path):
    """DB.open persists OPTIONS-NNNN; load_latest_options rebuilds an
    equivalent Options (reference PersistRocksDBOptions/LoadLatestOptions)."""
    import os

    from toplingdb_tpu.utils.config import load_latest_options
    from toplingdb_tpu.utils.merge_operator import UInt64AddOperator

    from toplingdb_tpu.table.filter import BloomFilterPolicy
    from toplingdb_tpu.utils.compaction_filter import (
        RemoveEmptyValueCompactionFilter,
    )

    o = opts(write_buffer_size=12345, compaction_style="universal",
             merge_operator=UInt64AddOperator(), num_levels=5,
             compaction_filter=RemoveEmptyValueCompactionFilter())
    o.table_options.block_size = 8192
    o.table_options.index_type = "two_level"
    o.table_options.filter_policy = BloomFilterPolicy(20.0)
    with DB.open(tmp_db_path, o) as db:
        db.put(b"k", b"v")
    assert any(f.startswith("OPTIONS-") for f in os.listdir(tmp_db_path))
    loaded = load_latest_options(tmp_db_path)
    assert loaded.write_buffer_size == 12345
    assert loaded.compaction_style == "universal"
    assert loaded.num_levels == 5
    assert loaded.merge_operator.name() == "UInt64AddOperator"
    assert loaded.table_options.block_size == 8192
    assert loaded.table_options.index_type == "two_level"
    assert loaded.table_options.filter_policy.bits_per_key == 20.0
    assert loaded.compaction_filter.name() == \
        "RemoveEmptyValueCompactionFilter"
    # Reopen rolls a fresh OPTIONS file and GCs the old one.
    with DB.open(tmp_db_path, o) as db:
        files = [f for f in os.listdir(tmp_db_path) if f.startswith("OPTIONS-")]
        assert len(files) == 1


def test_overlay_env(mem_env, tmp_path):
    """OverlayEnv (reference CatFileSystem, env/fs_cat.cc): reads fall
    through to the base, writes land in the overlay, deletes/renames never
    touch the base."""
    from toplingdb_tpu.env import MemEnv
    from toplingdb_tpu.env.overlay import OverlayEnv
    from toplingdb_tpu.utils.status import NotFound

    base = mem_env
    base.create_dir("/db")
    base.write_file("/db/000010.sst", b"BASE-SST")
    base.write_file("/db/CURRENT", b"MANIFEST-000002\n")
    over = MemEnv()
    over.create_dir("/db")
    env = OverlayEnv(base, over)

    assert env.read_file("/db/000010.sst") == b"BASE-SST"
    env.write_file("/db/000020.sst", b"NEW-SST")
    assert env.read_file("/db/000020.sst") == b"NEW-SST"
    assert not base.file_exists("/db/000020.sst"), "write leaked to base"
    assert sorted(env.get_children("/db")) == [
        "000010.sst", "000020.sst", "CURRENT"]

    # Overlay shadows base on same path.
    env.write_file("/db/CURRENT", b"MANIFEST-000009\n")
    assert env.read_file("/db/CURRENT") == b"MANIFEST-000009\n"
    assert base.read_file("/db/CURRENT") == b"MANIFEST-000002\n"

    # Delete of a base file = whiteout; base untouched.
    env.delete_file("/db/000010.sst")
    assert not env.file_exists("/db/000010.sst")
    assert base.file_exists("/db/000010.sst")
    with pytest.raises(NotFound):
        env.read_file("/db/000010.sst")
    assert env.get_children("/db") == ["000020.sst", "CURRENT"]

    # Rename of a base file copies up + whiteouts the source.
    base.write_file("/db/000011.sst", b"B11")
    env.rename_file("/db/000011.sst", "/db/000030.sst")
    assert env.read_file("/db/000030.sst") == b"B11"
    assert not env.file_exists("/db/000011.sst")
    assert base.file_exists("/db/000011.sst")


def test_worker_reads_through_overlay_env(tmp_path):
    """A read-only base DB dir + overlay: a DB opens and reads through
    OverlayEnv without writing to the base (the dcompact worker mount
    pattern)."""
    import os

    from toplingdb_tpu.env import MemEnv, PosixEnv
    from toplingdb_tpu.env.overlay import OverlayEnv

    src = str(tmp_path / "primary")
    with DB.open(src, opts()) as db:
        for i in range(300):
            db.put(b"k%04d" % i, b"v%04d" % i)
        db.flush()
    before = sorted(os.listdir(src))
    over = MemEnv()
    over.create_dir(src)
    env = OverlayEnv(PosixEnv(), over)
    from toplingdb_tpu.db.db import DB as DB2

    db2 = DB2.open(src, opts(), env=env)
    assert db2.get(b"k0123") == b"v0123"
    db2.put(b"extra", b"x")
    db2.flush()
    assert db2.get(b"extra") == b"x"
    db2.close()
    assert sorted(os.listdir(src)) == before, "base dir was modified!"


def test_io_tracing_env(tmp_path):
    """IOTracingEnv records file ops as JSONL; parse_io_trace aggregates
    (reference io_tracer + io_tracer_parser)."""
    from toplingdb_tpu.env import PosixEnv
    from toplingdb_tpu.env.io_tracer import IOTracer, IOTracingEnv, parse_io_trace

    trace = str(tmp_path / "io.trace")
    tracer = IOTracer(trace)
    env = IOTracingEnv(PosixEnv(), tracer)
    d = str(tmp_path / "db")
    with DB.open(d, opts(), env=env) as db:
        for i in range(200):
            db.put(b"k%04d" % i, b"v" * 50)
        db.flush()
        assert db.get(b"k0100") == b"v" * 50
    tracer.close()
    agg = parse_io_trace(trace)
    assert agg["append"]["count"] > 0 and agg["append"]["bytes"] > 0
    assert "sync" in agg and "read" in agg
    assert agg["read"]["bytes"] > 0


def test_two_phase_commit_recovery(tmp_path):
    """2PC: a prepared transaction survives a crash and can be committed or
    rolled back after recovery (reference Prepare/GetAllPreparedTransactions)."""
    from toplingdb_tpu.utilities.transactions import TransactionDB

    d = str(tmp_path / "db")
    tdb = TransactionDB.open(d, opts())
    tdb.put(b"base", b"v")
    t1 = tdb.begin_transaction()
    t1.set_name("t1")
    t1.put(b"pk", b"pv")
    t1.prepare()
    t2 = tdb.begin_transaction()
    t2.set_name("t2")
    t2.put(b"rk", b"rv")
    t2.prepare()
    # Crash: no commit, no clean close.
    tdb.db._wal.sync()
    tdb.db._closed = True
    tdb.db._compaction_scheduler.shutdown()

    tdb2 = TransactionDB.open(d, opts())
    assert tdb2.get(b"pk") is None, "prepared data must not be visible"
    recovered = {t.name: t for t in tdb2.get_prepared_transactions()}
    assert set(recovered) == {"t1", "t2"}
    recovered["t1"].commit()
    recovered["t2"].rollback()
    assert tdb2.get(b"pk") == b"pv"
    assert tdb2.get(b"rk") is None
    assert tdb2.get(b"base") == b"v"
    tdb2.close()
    # After a clean cycle nothing is pending and data persists.
    tdb3 = TransactionDB.open(d, opts())
    assert tdb3.get_prepared_transactions() == []
    assert tdb3.get(b"pk") == b"pv"
    tdb3.close()


def test_two_phase_commit_crash_after_commit_write(tmp_path):
    """Crash between the commit write and the prep-file delete must NOT
    double-apply on recovery (the hidden commit marker resolves it)."""
    from toplingdb_tpu.utilities.transactions import TransactionDB

    d = str(tmp_path / "db")
    tdb = TransactionDB.open(d, opts())
    t = tdb.begin_transaction()
    t.set_name("tx")
    t.put(b"k", b"v1")
    t.prepare()
    # Simulate the torn commit: write the batch+marker but keep the prep
    # file (as if we crashed before deleting it).
    from toplingdb_tpu.db.write_batch import WriteBatch

    marker = TransactionDB._MARKER_PREFIX + b"tx"
    batch = WriteBatch(t.wbwi.batch.data())
    batch.put(marker, b"1", cf=tdb._txn_cf.id)
    tdb.db.write(batch)
    tdb.db._wal.sync()
    tdb.db._closed = True
    tdb.db._compaction_scheduler.shutdown()

    tdb2 = TransactionDB.open(d, opts())
    assert tdb2.get_prepared_transactions() == [], \
        "already-committed txn offered again"
    assert tdb2.get(b"k") == b"v1"
    assert tdb2.db.get(marker, cf=tdb2._txn_cf) is None, "marker must be swept"
    tdb2.close()


def test_http_setoptions(tmp_path):
    """POST /setoptions/<db> applies dynamic option changes (the rockside
    online-config role)."""
    import urllib.request as rq

    from toplingdb_tpu.utils.config import SidePluginRepo

    repo = SidePluginRepo()
    repo.open_db({"path": str(tmp_path / "db"), "name": "d1", "options": {}})
    port = repo.start_http()
    req = rq.Request(
        f"http://127.0.0.1:{port}/setoptions/d1",
        data=json.dumps({"write_buffer_size": 777_777}).encode(),
        method="POST",
    )
    body = json.loads(rq.urlopen(req).read())
    assert body["ok"] is True
    assert repo.get_db("d1").options.write_buffer_size == 777_777
    # Bad option → 400.
    req = rq.Request(
        f"http://127.0.0.1:{port}/setoptions/d1",
        data=json.dumps({"num_levels": 2}).encode(), method="POST",
    )
    try:
        rq.urlopen(req)
        raise AssertionError("expected HTTP 400")
    except Exception as e:
        assert getattr(e, "code", None) == 400
    repo.close_all()


def test_clock_cache(tmp_db_path):
    from toplingdb_tpu.utils.cache import ClockCache

    c = ClockCache(1000)
    for i in range(10):
        c.insert(b"k%02d" % i, b"x" * 90, 100)
    assert c.usage() <= 1000
    # Touch a subset: their ref bits protect them through the next sweep.
    for i in (0, 1):
        c.lookup(b"k%02d" % i)
    for i in range(10, 16):
        c.insert(b"k%02d" % i, b"y" * 90, 100)
    assert c.usage() <= 1000
    c.erase(b"k15")
    assert c.lookup(b"k15") is None
    # As a DB block cache.
    from toplingdb_tpu.db.db import DB

    with DB.open(tmp_db_path, opts(block_cache=ClockCache(64 * 1024),
                                   disable_auto_compactions=True)) as db:
        for i in range(2000):
            db.put(b"key%05d" % i, b"v%05d" % i)
        db.flush()
        for i in range(0, 2000, 7):
            assert db.get(b"key%05d" % i) == b"v%05d" % i
        assert db.options.block_cache.hits > 0


def test_compressed_secondary_cache(tmp_db_path):
    from toplingdb_tpu.utils.cache import CompressedSecondaryCache, LRUCache

    sec = CompressedSecondaryCache(1 << 20)
    lru = LRUCache(2 * 1024, num_shards=1, secondary=sec)
    blocks = {b"b%02d" % i: (b"content-%02d" % i) * 40 for i in range(20)}
    for k, v in blocks.items():
        lru.insert(k, v, len(v))
    # Early blocks spilled compressed; lookup decompresses + promotes.
    assert lru.lookup(b"b00") == blocks[b"b00"]
    assert sec.hits >= 1
    assert sec.usage() < sum(len(v) for v in blocks.values()), \
        "tier must actually compress"
    sec.erase(b"b01")
    lru2 = LRUCache(1024, num_shards=1, secondary=sec)
    assert lru2.lookup(b"b01") is None


def test_auto_sort_table_builder(tmp_path):
    """VecAutoSortTable role: unsorted bulk adds sort at finish with
    last-write-wins on duplicates."""
    import random

    from toplingdb_tpu.db import dbformat
    from toplingdb_tpu.db.dbformat import InternalKeyComparator, ValueType
    from toplingdb_tpu.env import PosixEnv
    from toplingdb_tpu.table.builder import TableOptions
    from toplingdb_tpu.table.factory import new_table_builder, open_table

    env = PosixEnv()
    icmp = InternalKeyComparator(dbformat.BYTEWISE)
    path = str(tmp_path / "auto.sst")
    w = env.new_writable_file(path)
    topts = TableOptions(format="single_fast", auto_sort=True)
    b = new_table_builder(w, icmp, topts)
    rng = random.Random(4)
    keys = list(range(500))
    rng.shuffle(keys)
    for i in keys:
        b.add(dbformat.make_internal_key(b"k%04d" % i, 7, ValueType.VALUE),
              b"old%04d" % i)
    # Duplicate internal key: the LAST add must win.
    b.add(dbformat.make_internal_key(b"k0042", 7, ValueType.VALUE), b"NEW")
    props = b.finish()
    w.close()
    assert props.num_entries == 500
    r = open_table(env.new_random_access_file(path), icmp, topts)
    it = r.new_iterator()
    it.seek_to_first()
    got = list(it.entries())
    assert [k[:-8] for k, _ in got] == [b"k%04d" % i for i in range(500)]
    assert dict((k[:-8], v) for k, v in got)[b"k0042"] == b"NEW"


def test_option_change_migration(tmp_path):
    from toplingdb_tpu.utilities.option_migration import migrate_options

    d = str(tmp_path / "db")
    leveled = opts(compaction_style="leveled", disable_auto_compactions=True)
    with DB.open(d, leveled) as db:
        for i in range(3000):
            db.put(b"key%05d" % i, b"v%05d" % i)
        db.flush()
        db.compact_range()
    # leveled → fifo: every file must end up in L0.
    fifo = opts(compaction_style="fifo", disable_auto_compactions=True)
    migrate_options(d, leveled, fifo)
    with DB.open(d, fifo) as db:
        v = db.versions.current
        assert all(not v.files[lvl] for lvl in range(1, v.num_levels)), \
            "files left outside L0 after fifo migration"
        assert db.get(b"key01500") == b"v01500"
    # fifo → universal round trip stays readable.
    uni = opts(compaction_style="universal", disable_auto_compactions=True)
    migrate_options(d, fifo, uni)
    with DB.open(d, uni) as db:
        assert db.get(b"key02999") == b"v02999"


def test_auto_recovery_from_retryable_error(tmp_path):
    """A retryable background IO error auto-resumes (reference
    StartRecoverFromRetryableBGIOError) without a manual resume()."""
    import time as _t

    from toplingdb_tpu.utils.status import IOError_

    d = str(tmp_path / "db")
    with DB.open(d, opts()) as db:
        db.put(b"a", b"1")
        db._set_background_error(IOError_("transient", retryable=True))
        deadline = _t.time() + 5.0
        while db._bg_error is not None and _t.time() < deadline:
            _t.sleep(0.02)
        assert db._bg_error is None, "auto recovery never cleared the error"
        db.put(b"b", b"2")  # writes work again
        assert db.get(b"b") == b"2"
        # NON-retryable errors stay latched until manual resume().
        db._set_background_error(IOError_("permanent"))
        _t.sleep(0.3)
        assert db._bg_error is not None
        db.resume()
        db.put(b"c", b"3")


def test_encrypted_env(tmp_path):
    """EncryptedEnv: a full DB lives encrypted at rest; ciphertext on disk,
    plaintext through the Env; wrong key fails loudly (reference
    env_encryption.cc)."""
    from toplingdb_tpu.env import PosixEnv
    from toplingdb_tpu.env.encrypted import CTRCipher, EncryptedEnv
    from toplingdb_tpu.utils.status import Corruption

    d = str(tmp_path / "db")
    env = EncryptedEnv(PosixEnv(), CTRCipher(b"key-material-1"))
    db = DB.open(d, opts(disable_auto_compactions=True), env=env)
    for i in range(500):
        db.put(b"secret%03d" % i, b"value%03d" % i)
    db.flush()
    db.compact_range()
    assert db.get(b"secret250") == b"value250"
    db.close()
    # Raw bytes on disk are ciphertext: the plaintext keys must not appear.
    import os

    blob = b"".join(
        open(os.path.join(d, f), "rb").read() for f in os.listdir(d)
        if os.path.isfile(os.path.join(d, f))
    )
    assert b"secret250" not in blob, "plaintext leaked to disk"
    # Reopen with the right key works; wrong key fails loudly.
    db2 = DB.open(d, opts(), env=EncryptedEnv(PosixEnv(),
                                              CTRCipher(b"key-material-1")))
    assert db2.get(b"secret499") == b"value499"
    db2.close()
    with pytest.raises(Corruption):
        DB.open(d, opts(), env=EncryptedEnv(PosixEnv(),
                                            CTRCipher(b"WRONG")))


def test_sim_cache(tmp_db_path):
    from toplingdb_tpu.utils.cache import LRUCache, SimCache

    sim = SimCache(LRUCache(4 * 1024, num_shards=1), 1 << 20)
    for i in range(64):
        sim.insert(b"k%02d" % i, b"x" * 256, 256)
    for i in range(64):
        sim.lookup(b"k%02d" % i)
    # The small REAL cache misses most; the simulated big one hits all.
    assert sim.sim_hit_rate() > 0.9
    assert sim.hit_rate() < 0.5
    # As a DB block cache.
    from toplingdb_tpu.db.db import DB as _DB

    with _DB.open(tmp_db_path, opts(
            block_cache=SimCache(LRUCache(4 * 1024), 1 << 22),
            disable_auto_compactions=True)) as db:
        for i in range(2000):
            db.put(b"key%05d" % i, b"v" * 30)
        db.flush()
        for _ in range(2):
            for i in range(0, 2000, 10):
                assert db.get(b"key%05d" % i) == b"v" * 30
        bc = db.options.block_cache
        assert bc.sim_hit_rate() > bc.hit_rate(), \
            "bigger simulated capacity should hit more"


def test_thread_status_registry(tmp_db_path):
    """Background ops report to the thread-status registry (reference
    monitoring/thread_status_updater.cc); visible via tpulsm.threads."""
    from toplingdb_tpu.utils.sync_point import get_sync_point_registry
    from toplingdb_tpu.utils.thread_status import (
        get_thread_list, thread_operation,
    )

    with thread_operation("unit-op", "stage1", "mydb"):
        rows = [r for r in get_thread_list() if r["operation"] == "unit-op"]
        assert rows and rows[0]["stage"] == "stage1"
        assert rows[0]["db"] == "mydb"
    assert not [r for r in get_thread_list() if r["operation"] == "unit-op"]

    # A real compaction reports itself: pause it mid-install and look.
    seen = []
    sp = get_sync_point_registry()
    sp.set_callback("CompactionJob::BeforeInstall",
                    lambda c: seen.extend(get_thread_list()))
    sp.enable_processing()
    try:
        with DB.open(tmp_db_path, opts(disable_auto_compactions=True)) as db:
            for i in range(300):
                db.put(b"k%03d" % i, b"v")
            db.flush()
            db.compact_range()

            def strip(rows):
                return [{k: v for k, v in r.items() if k != "elapsed_s"}
                        for r in rows]

            assert strip(json.loads(db.get_property("tpulsm.threads"))) == \
                strip(get_thread_list())
    finally:
        sp.clear_all()
    assert any(r["operation"] == "compaction" for r in seen), seen


def test_wbwi_skiplist_rep_matches_list_rep():
    """The native-skiplist WBWI index (CSPP_WBWI role) behaves identically
    to the sorted-list baseline across put/delete/merge interleavings."""
    import random

    from toplingdb_tpu.utilities.write_batch_with_index import (
        WriteBatchWithIndex,
    )
    from toplingdb_tpu.utils.merge_operator import StringAppendOperator

    from toplingdb_tpu import native

    if native.lib() is None:
        pytest.skip("native library unavailable")
    rng = random.Random(2)
    ops = [(rng.choice("PPDM"), b"k%03d" % rng.randrange(150),
            b"v%04d" % i) for i in range(3000)]
    views = {}
    for rep in ("list", "skiplist"):
        w = WriteBatchWithIndex(StringAppendOperator(), rep=rep)
        for op, k, v in ops:
            if op == "P":
                w.put(k, v)
            elif op == "D":
                w.delete(k)
            else:
                w.merge(k, v)
        views[rep] = ({k: w.get_from_batch(k) for k in w.key_set()},
                      w.key_set())
    assert views["list"] == views["skiplist"]


# -- range locking (Toku locktree role) -------------------------------------


def test_range_lock_conflict_and_release(tmp_path):
    """A locked interval blocks writes to ANY key inside it; release at
    commit unblocks (reference utilities/transactions/lock/range/)."""
    from toplingdb_tpu.utilities.transactions import TransactionDB
    from toplingdb_tpu.utils.status import Busy

    with TransactionDB.open(str(tmp_path / "db"),
                            use_range_locking=True) as tdb:
        t1 = tdb.begin_transaction()
        t1.get_range_lock(b"k20", b"k40")
        t1.put(b"k25", b"t1")  # inside own range: no self-conflict
        t2 = tdb.begin_transaction(lock_timeout=0.1)
        t2.put(b"k10", b"t2")  # outside the range: fine
        with pytest.raises(Busy):
            t2.put(b"k30", b"t2")  # inside t1's range: blocked
        with pytest.raises(Busy):
            t2.get_range_lock(b"k39", b"k99")  # overlapping range: blocked
        t1.commit()
        t2.put(b"k30", b"t2")  # released
        t2.get_range_lock(b"k39", b"k99")
        t2.commit()
        assert tdb.get(b"k25") == b"t1"
        assert tdb.get(b"k30") == b"t2"


def test_range_lock_deadlock_detection(tmp_path):
    from toplingdb_tpu.utilities.transactions import (
        DeadlockError, TransactionDB,
    )
    import threading

    with TransactionDB.open(str(tmp_path / "db"),
                            use_range_locking=True) as tdb:
        t1 = tdb.begin_transaction(lock_timeout=5.0)
        t2 = tdb.begin_transaction(lock_timeout=5.0)
        t1.get_range_lock(b"a", b"c")
        t2.get_range_lock(b"x", b"z")
        errs = []

        def t2_crosses():
            try:
                t2.get_range_lock(b"b", b"b")  # waits on t1
            except Exception as e:
                errs.append(e)

        th = threading.Thread(target=t2_crosses)
        th.start()
        import time as _t

        _t.sleep(0.1)
        with pytest.raises(DeadlockError):
            t1.get_range_lock(b"y", b"y")  # t1→t2 while t2→t1: cycle
        t1.rollback()
        th.join()
        t2.rollback()


def test_range_lock_escalation():
    """Holding more than max_ranges_per_txn ranges merges consecutive owned
    ranges into hulls (Toku lock escalation: bounded memory, safe
    over-locking)."""
    from toplingdb_tpu.utilities.transactions import RangeLockManager

    mgr = RangeLockManager(max_ranges_per_txn=8)
    for i in range(40):
        k = b"k%04d" % (i * 2)  # disjoint single-key ranges
        mgr.try_lock_range(1, k, k)
    assert len(mgr._ranges) <= 8 + 1
    # The hull covers everything in between — another txn is kept out.
    from toplingdb_tpu.utils.status import Busy

    with pytest.raises(Busy):
        mgr.try_lock_range(2, b"k0001", b"k0001", timeout=0.05)
    mgr.unlock_all(1)
    mgr.try_lock_range(2, b"k0001", b"k0001", timeout=0.05)


def test_range_lock_merges_own_overlaps():
    from toplingdb_tpu.utilities.transactions import RangeLockManager

    mgr = RangeLockManager()
    mgr.try_lock_range(7, b"a", b"f")
    mgr.try_lock_range(7, b"d", b"m")   # overlaps own: merged to [a, m]
    mgr.try_lock_range(7, b"m", b"p")
    assert len(mgr._ranges) <= 2
    covered = mgr._overlaps(b"a", b"p")
    assert all(r[2] == 7 for r in covered)
    from toplingdb_tpu.utils.status import InvalidArgument

    with pytest.raises(InvalidArgument):
        mgr.try_lock_range(7, b"z", b"a")


def test_range_lock_multi_holder_deadlock():
    """Cycles through ANY holder of an overlapping range are detected —
    single-edge tracking would miss them (t3 waits on {t1, t2})."""
    from toplingdb_tpu.utilities.transactions import (
        DeadlockError, RangeLockManager,
    )
    import threading
    import time as _t

    mgr = RangeLockManager()
    mgr.try_lock_range(1, b"a", b"b")
    mgr.try_lock_range(2, b"c", b"d")
    res = {}

    def t3_wants_both():
        try:
            mgr.try_lock_range(3, b"a", b"d", timeout=5.0)
            res["t3"] = "got"
        except Exception as e:
            res["t3"] = type(e).__name__

    th = threading.Thread(target=t3_wants_both)
    th.start()
    _t.sleep(0.15)
    # t3 waits on BOTH holders (multi-edge), not an arbitrary one.
    with mgr._cv:
        assert mgr._waits_for.get(3) == {1, 2}
    # Cycle through the SECOND holder: t3 already holds [m,n]? it holds
    # nothing — so create one via a 4th txn chain: t2 waits on t4, t4
    # requests t1's... keep it direct: t1 (a holder t3 waits on) requests
    # a range held by a txn that waits on t3 — t4 holds [p,q], waits on
    # t3's pending? t3 holds nothing while blocked. Exercise instead the
    # detector over set-valued edges: t2 requests a range of t4 where t4
    # waits on t3 — the t3→{1,2} edge closes t2→t4→t3→t2.
    mgr.try_lock_range(4, b"p", b"q")
    wait4 = {}

    def t4_waits_on_t3_target():
        # t4 requests inside [a,d] — blocked by t1/t2 alongside t3; record
        # its edge then time out quickly.
        try:
            mgr.try_lock_range(4, b"a", b"a", timeout=0.2)
            wait4["r"] = "got"
        except Exception as e:
            wait4["r"] = type(e).__name__

    th4 = threading.Thread(target=t4_waits_on_t3_target)
    th4.start()
    _t.sleep(0.05)
    with pytest.raises(DeadlockError):
        # t1 requests t4's range: t1 → t4 → {t1, t2} closes the cycle
        # through the holder-SET edge.
        mgr.try_lock_range(1, b"p", b"p", timeout=1.0)
    th4.join()
    mgr.unlock_all(1)
    mgr.unlock_all(2)
    mgr.unlock_all(4)
    th.join()
    assert res["t3"] == "got"


def test_range_lock_2pc_recovery(tmp_path):
    """A prepared transaction's RANGE locks survive crash recovery: the gap
    stays protected until the recovered txn is decided."""
    import os
    import subprocess
    import sys

    dbp = str(tmp_path / "db")
    child = f'''
import sys, os
sys.path.insert(0, {os.getcwd()!r})
from toplingdb_tpu.utilities.transactions import TransactionDB
tdb = TransactionDB.open({dbp!r}, use_range_locking=True)
t = tdb.begin_transaction()
t.get_range_lock(b"g100", b"g200")
t.put(b"g150", b"prepared-val")
t.set_name("gaplock")
t.prepare()
os._exit(0)  # crash before deciding
'''
    r = subprocess.run([sys.executable, "-c", child], capture_output=True,
                       text=True)
    assert r.returncode == 0, r.stderr
    from toplingdb_tpu.utilities.transactions import TransactionDB
    from toplingdb_tpu.utils.status import Busy, InvalidArgument

    # Reopening WITHOUT range locking refuses (the gap cannot be protected).
    with pytest.raises(InvalidArgument):
        TransactionDB.open(dbp)
    tdb = TransactionDB.open(dbp, use_range_locking=True)
    [rec] = tdb.get_prepared_transactions()
    assert rec.name == "gaplock"
    t2 = tdb.begin_transaction(lock_timeout=0.05)
    with pytest.raises(Busy):
        t2.put(b"g175", b"intruder")  # inside the recovered range
    rec.commit()
    t2.put(b"g175", b"after-commit")
    t2.commit()
    assert tdb.get(b"g150") == b"prepared-val"
    assert tdb.get(b"g175") == b"after-commit"
    tdb.close()


def test_repo_webview_dashboard(tmp_path):
    """The rockside WebView role: HTML dashboard over the repo HTTP
    server — DB list, per-DB page with levels/tickers/config, and the
    online-options form target actually applies changes."""
    import json as _json
    import urllib.request

    from toplingdb_tpu.utils.config import SidePluginRepo

    repo = SidePluginRepo()
    db = repo.open_db({"path": str(tmp_path / "db"), "name": "web",
                       "options": {"create_if_missing": True}})
    for i in range(500):
        db.put(b"k%04d" % i, b"v" * 20)
    db.flush()
    port = repo.start_http(0)
    try:
        idx = urllib.request.urlopen(
            f"http://127.0.0.1:{port}/view").read().decode()
        assert "web" in idx and "/view/web" in idx
        page = urllib.request.urlopen(
            f"http://127.0.0.1:{port}/view/web").read().decode()
        assert "Levels" in page and "setoptions/web" in page
        # the online-config endpoint the form posts to
        req = urllib.request.Request(
            f"http://127.0.0.1:{port}/setoptions/web",
            data=_json.dumps({"write_buffer_size": 1 << 20}).encode(),
            method="POST")
        resp = _json.loads(urllib.request.urlopen(req).read())
        assert resp["ok"]
        assert db.options.write_buffer_size == 1 << 20
        import urllib.error

        try:
            urllib.request.urlopen(f"http://127.0.0.1:{port}/view/nope")
            assert False, "unknown db must 404"
        except urllib.error.HTTPError as e:
            assert e.code == 404
    finally:
        repo.close_all()


def test_backup_engine_depth(tmp_path):
    """delete_backup / verify_backup / garbage_collect / app metadata
    (reference backup_engine.cc surfaces beyond create+restore)."""
    import pytest as _pytest

    from toplingdb_tpu.db.db import DB
    from toplingdb_tpu.options import Options
    from toplingdb_tpu.utilities.backup_engine import BackupEngine
    from toplingdb_tpu.utils.status import Corruption, NotFound

    be = BackupEngine(str(tmp_path / "backups"))
    with DB.open(str(tmp_path / "db"),
                 Options(create_if_missing=True)) as db:
        for i in range(300):
            db.put(b"k%04d" % i, b"v" * 40)
        db.flush()
        b1 = be.create_backup(db, app_metadata="first")
        for i in range(300, 600):
            db.put(b"k%04d" % i, b"v" * 40)
        db.flush()
        b2 = be.create_backup(db)
    infos = be.get_backup_info()
    assert [i["backup_id"] for i in infos] == [b1, b2]
    assert infos[0]["app_metadata"] == "first"
    assert infos[0]["timestamp"] > 0
    be.verify_backup(b1)
    be.verify_backup(b2)
    # corrupt one of B1'S OWN shared files: verify must catch it
    import json as _json
    import os

    with open(str(tmp_path / f"backups/meta/{b1}.json")) as f:
        victim_name = _json.load(f)["files"][0]["shared"]
    victim = str(tmp_path / "backups/shared" / victim_name)
    blob = bytearray(open(victim, "rb").read())
    blob[30] ^= 0xFF
    open(victim, "wb").write(bytes(blob))
    with _pytest.raises(Corruption):
        be.verify_backup(b1)
    open(victim, "wb").write(bytes(blob[:30]) + bytes([blob[30] ^ 0xFF])
                             + bytes(blob[31:]))
    be.verify_backup(b1)
    # delete b1: shared files still used by b2 survive; b2 restorable
    be.delete_backup(b1)
    with _pytest.raises(NotFound):
        be.verify_backup(b1)
    be.verify_backup(b2)
    be.restore_db_from_backup(b2, str(tmp_path / "restored"))
    with DB.open(str(tmp_path / "restored"), Options()) as db2:
        assert db2.get(b"k0000") == b"v" * 40
        assert db2.get(b"k0599") == b"v" * 40
    # orphaned shared file: gc removes it
    orphan = str(tmp_path / "backups/shared/999_deadbeef_000001.sst")
    open(orphan, "wb").write(b"junk")
    assert be.garbage_collect() >= 1
    assert not os.path.exists(orphan)
