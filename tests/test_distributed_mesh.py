"""Distributed mesh compaction step (jobs x range axes): the dryrun's
validation as a pytest — plain, merge-bearing, and tombstone-bearing jobs
on an 8-virtual-device CPU mesh, cross-checked against the single-chip
kernels (VERDICT r2 task 8)."""


def test_dryrun_multichip_8():
    import __graft_entry__ as ge

    ge.dryrun_multichip(8)


def test_dryrun_multichip_4():
    import __graft_entry__ as ge

    ge.dryrun_multichip(4)
