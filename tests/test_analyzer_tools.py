"""The trace-analysis CLI tools (reference trace_analyzer,
io_tracer_parser, block_cache_analyzer binaries)."""

import json
import os

from toplingdb_tpu.db.db import DB
from toplingdb_tpu.options import Options


def test_trace_analyzer_tool(tmp_path, capsys):
    from toplingdb_tpu.tools import trace_analyzer
    from toplingdb_tpu.utils.trace import Tracer

    dbp = str(tmp_path / "db")
    trace = str(tmp_path / "trace.bin")
    with DB.open(dbp, Options()) as db:
        t = Tracer(db, trace)
        for i in range(60):
            t.put(b"key%03d" % (i % 20), b"v" * (i % 7 + 1))
        for i in range(40):
            t.get(b"key%03d" % (i % 10))
        t.delete(b"key001")
        t.close()

    report = trace_analyzer.analyze(db.env, trace)
    assert report["total_ops"] == 101
    assert report["per_op"] == {"put": 60, "get": 40, "delete": 1}
    assert report["unique_keys"] == 20
    assert report["hottest_keys"][0]["count"] >= 7
    assert report["key_size_dist"]["p50"] == 6
    assert report["value_size_dist"]["max"] == 7

    outdir = str(tmp_path / "out")
    rc = trace_analyzer.main(
        [trace, "--json", "--output-dir", outdir, "-k", "3"]
    )
    assert rc == 0
    printed = json.loads(capsys.readouterr().out)
    assert printed["total_ops"] == 101 and len(printed["hottest_keys"]) == 3
    files = sorted(os.listdir(outdir))
    assert files == ["delete-key_counts.txt", "get-key_counts.txt",
                     "put-key_counts.txt"]
    first = open(os.path.join(outdir, "get-key_counts.txt")).readline().split()
    assert int(first[1]) == 4  # hottest get key: 40 gets over 10 keys

    # Human-readable mode exercises the non-json printer.
    assert trace_analyzer.main([trace]) == 0
    assert "hottest keys" in capsys.readouterr().out


def test_io_tracer_parser_tool(tmp_path, capsys):
    from toplingdb_tpu.env.io_tracer import IOTracer, IOTracingEnv
    from toplingdb_tpu.env import PosixEnv
    from toplingdb_tpu.tools import io_tracer_parser

    trace = str(tmp_path / "io.jsonl")
    tracer = IOTracer(trace)
    env = IOTracingEnv(PosixEnv(), tracer)
    f = env.new_writable_file(str(tmp_path / "a.bin"))
    f.append(b"x" * 1000)
    f.sync()
    f.close()
    r = env.new_random_access_file(str(tmp_path / "a.bin"))
    r.read(0, 100)
    r.read(500, 100)
    tracer.close()

    report = io_tracer_parser.parse(trace)
    assert report["total_records"] >= 4
    assert report["per_op"]["append"]["bytes"] == 1000
    assert report["per_op"]["read"]["count"] == 2
    assert any(p.endswith("a.bin") for p in report["per_file"])

    assert io_tracer_parser.main([trace, "--json"]) == 0
    assert json.loads(capsys.readouterr().out)["total_records"] >= 4
    assert io_tracer_parser.main([trace]) == 0
    assert "top files by bytes" in capsys.readouterr().out


def test_block_cache_analyzer_tool(tmp_path, capsys):
    from toplingdb_tpu.tools import block_cache_analyzer
    from toplingdb_tpu.utils.cache import BlockCacheTracer, LRUCache

    trace = str(tmp_path / "bc.jsonl")
    tracer = BlockCacheTracer(trace)
    cache = LRUCache(1 << 20, tracer=tracer)
    for rep in range(3):
        for i in range(10):
            k = b"block-%03d" % i
            if cache.lookup(k) is None:
                cache.insert(k, b"data" * 10, charge=40)
    tracer.close()

    report = block_cache_analyzer.analyze(trace)
    assert report["accesses"] == 30
    assert report["misses"] == 10 and report["hits"] == 20
    assert abs(report["hit_ratio"] - 20 / 30) < 1e-4  # report rounds to 4dp
    assert report["unique_blocks"] == 10
    assert report["hottest_blocks"][0]["accesses"] == 3

    assert block_cache_analyzer.main([trace, "--json"]) == 0
    assert json.loads(capsys.readouterr().out)["accesses"] == 30
    assert block_cache_analyzer.main([trace, "-n", "2"]) == 0
    assert "hit ratio" in capsys.readouterr().out


def test_blob_dump_tool(tmp_path):
    """blob_dump walks records, verifies CRCs, and flags corruption
    (reference tools/blob_dump.cc role)."""
    from toplingdb_tpu.db.blob import BlobFileBuilder, blob_file_name
    from toplingdb_tpu.env import default_env
    from toplingdb_tpu.tools.blob_dump import dump_blob_file

    env = default_env()
    d = str(tmp_path)
    b = BlobFileBuilder(env, d, 7)
    for i in range(25):
        b.add(b"key%02d" % i, b"v" * (100 + i))
    assert b.finish() == 25
    path = blob_file_name(d, 7)
    s = dump_blob_file(path)
    assert s["records"] == 25 and s["bad_crc"] == 0
    assert s["corrupt_at"] is None
    # flip a value byte: exactly one record's crc goes bad
    blob = bytearray(open(path, "rb").read())
    blob[40] ^= 0xFF
    open(path, "wb").write(bytes(blob))
    s2 = dump_blob_file(path)
    assert s2["bad_crc"] >= 1
