"""Pipelined compaction data plane (ops/pipeline.py): byte parity with the
serial path across codecs and compute modes, clean cancellation, prefetch
ticker export, and a seeded pipeline soak."""

import os
import random
import shutil
import tempfile

import numpy as np
import pytest

from toplingdb_tpu.db.dbformat import (
    InternalKeyComparator,
    ValueType,
    make_internal_key,
)

ICMP = InternalKeyComparator()


def _build_runs(env, dbdir, n_total, topts, seed=1, runs=4, first_fnum=21,
                with_dels=True, tombstone_file=False):
    """Vectorized multi-run input builder: ~2x overwrite factor, optional
    deletions; optionally one per-entry file carrying a range tombstone."""
    import toplingdb_tpu.db.filename as fn
    from toplingdb_tpu.db.version_edit import FileMetaData
    from toplingdb_tpu.ops.columnar_io import ColumnarKV, write_tables_columnar
    from toplingdb_tpu.table.builder import TableBuilder

    rng = np.random.default_rng(seed)
    per_run = n_total // runs
    metas = []
    counter = [first_fnum - 1]

    def alloc():
        counter[0] += 1
        return counter[0]

    for run in range(runs):
        n = per_run
        draws = rng.integers(0, max(1, n_total // 2), n, dtype=np.int64)
        seqs = np.arange(run * per_run + 1, run * per_run + n + 1,
                         dtype=np.uint64)
        vts = np.full(n, int(ValueType.VALUE), dtype=np.uint64)
        if with_dels:
            vts[np.asarray(rng.random(n) < 0.15)] = int(ValueType.DELETION)
        ik = np.empty((n, 16), dtype=np.uint8)
        for j in range(8):
            ik[:, 7 - j] = (draws // 10 ** j) % 10 + ord("0")
        packed = (seqs << np.uint64(8)) | vts
        ik[:, 8:] = packed[:, None] >> (np.arange(8) * 8).astype(
            np.uint64)[None, :] & np.uint64(0xFF)
        vlens = np.where(vts == int(ValueType.VALUE), 20, 0).astype(np.int32)
        vals = np.full(int(vlens.sum()), ord("v"), dtype=np.uint8)
        s = np.lexsort((np.iinfo(np.int64).max - seqs.view(np.int64), draws))
        voffs = (np.cumsum(vlens[s]) - vlens[s]).astype(np.int32)
        kv = ColumnarKV(
            np.ascontiguousarray(ik[s]).reshape(-1),
            np.arange(n, dtype=np.int32) * 16,
            np.full(n, 16, dtype=np.int32),
            vals, voffs, vlens[s],
        )
        files = write_tables_columnar(
            env, dbdir, alloc, ICMP, topts, kv,
            np.arange(n, dtype=np.int32), np.full(n, -1, dtype=np.int64),
            vts.astype(np.int32)[s], seqs[s], [], creation_time=1,
        )
        for fnum, path, props, smallest, largest, _sel in files:
            metas.append(FileMetaData(
                number=fnum, file_size=env.get_file_size(path),
                smallest=smallest, largest=largest,
                smallest_seqno=props.smallest_seqno,
                largest_seqno=props.largest_seqno,
            ))
    if tombstone_file:
        fnum = alloc()
        w = env.new_writable_file(fn.table_file_name(dbdir, fnum))
        b = TableBuilder(w, ICMP, topts)
        base = n_total * 2
        for i in range(50):
            b.add(make_internal_key(b"%08d" % (i * 37), base + i,
                                    ValueType.VALUE), b"t%05d" % i)
        lo = b"%08d" % (n_total // 8)
        hi = b"%08d" % (n_total // 4)
        b.add_tombstone(make_internal_key(lo, base + 99,
                                          ValueType.RANGE_DELETION), hi)
        props = b.finish()
        w.close()
        metas.append(FileMetaData(
            number=fnum,
            file_size=env.get_file_size(fn.table_file_name(dbdir, fnum)),
            smallest=b.smallest_key, largest=b.largest_key,
            smallest_seqno=props.smallest_seqno,
            largest_seqno=props.largest_seqno,
        ))
    return metas


def _mk_alloc(base):
    s = [base]

    def alloc():
        s[0] += 1
        return s[0]

    return alloc


def _run_job(env, dbdir, metas, topts, out_topts, alloc_base, snapshots,
             device=True):
    from toplingdb_tpu.compaction.compaction_job import run_compaction_to_tables
    from toplingdb_tpu.compaction.picker import Compaction
    from toplingdb_tpu.db.table_cache import TableCache
    from toplingdb_tpu.ops.device_compaction import run_device_compaction

    tc = TableCache(env, dbdir, ICMP, topts)
    c = Compaction(level=0, output_level=2, inputs=list(metas),
                   bottommost=True, max_output_file_size=1 << 62)
    if device:
        return run_device_compaction(
            env, dbdir, ICMP, c, tc, out_topts, snapshots,
            new_file_number=_mk_alloc(alloc_base), creation_time=7,
            device_name="cpu-jax",
        )
    return run_compaction_to_tables(
        env, dbdir, ICMP, c, tc, out_topts, snapshots,
        new_file_number=_mk_alloc(alloc_base), creation_time=7,
    )


def _sst_bytes(env, dbdir, outs):
    import toplingdb_tpu.db.filename as fn

    return [open(fn.table_file_name(dbdir, m.number), "rb").read()
            for m in outs]


def _enable_small_pipeline(monkeypatch, shards=4):
    from toplingdb_tpu.ops import pipeline as pl

    monkeypatch.setattr(pl, "MIN_PIPELINE_ROWS", 256)
    monkeypatch.setenv("TPULSM_PIPELINE_SHARDS", str(shards))


def _spy_pipeline(monkeypatch):
    """Count successful run_pipelined invocations (parity tests must not
    silently degrade to the serial path)."""
    from toplingdb_tpu.ops import pipeline as pl

    calls = []
    orig = pl.run_pipelined

    def spy(*a, **k):
        r = orig(*a, **k)
        calls.append(1)
        return r

    monkeypatch.setattr(pl, "run_pipelined", spy)
    return calls


@pytest.mark.parametrize("codec", ["none", "snappy", "zstd"])
@pytest.mark.parametrize("mode", ["host", "device"])
def test_pipeline_byte_parity(tmp_path, monkeypatch, codec, mode):
    """Pipelined outputs are byte-identical to the serial path across
    codecs, compute modes, snapshots and a surviving range tombstone."""
    from toplingdb_tpu.env import default_env
    from toplingdb_tpu.table import format as fmt
    from toplingdb_tpu.table.builder import TableOptions
    from toplingdb_tpu.utils import codecs

    if mode == "device" and codec == "zstd":
        pytest.skip("device mode covered by none/snappy; zstd adds compile")
    comp = {"none": fmt.NO_COMPRESSION, "snappy": fmt.SNAPPY_COMPRESSION,
            "zstd": fmt.ZSTD_COMPRESSION}[codec]
    if codec != "none" and not codecs.available(codec):
        pytest.skip(f"{codec} unavailable")
    if mode == "host":
        monkeypatch.setenv("TPULSM_HOST_SORT", "1")
    else:
        monkeypatch.delenv("TPULSM_HOST_SORT", raising=False)
    _enable_small_pipeline(monkeypatch)
    calls = _spy_pipeline(monkeypatch)

    env = default_env()
    dbdir = str(tmp_path)
    topts = TableOptions(block_size=512, compression=comp)
    n = 24_000
    metas = _build_runs(env, dbdir, n, topts, seed=3, tombstone_file=True)
    snapshots = [n // 3, 2 * n // 3]

    monkeypatch.setenv("TPULSM_PIPELINE", "0")
    out_serial, _ = _run_job(env, dbdir, metas, topts, topts, 1000, snapshots)
    assert not calls
    monkeypatch.setenv("TPULSM_PIPELINE", "1")
    out_pipe, stats = _run_job(env, dbdir, metas, topts, topts, 2000,
                               snapshots)
    assert calls, "pipeline did not engage"
    assert stats.prefetch_misses > 0

    assert len(out_serial) == len(out_pipe) >= 1
    for a, b in zip(_sst_bytes(env, dbdir, out_serial),
                    _sst_bytes(env, dbdir, out_pipe)):
        assert a == b, "pipelined SST bytes differ from serial"
    for a, b in zip(out_serial, out_pipe):
        assert (a.smallest, a.largest, a.num_entries) == \
            (b.smallest, b.largest, b.num_entries)


def test_pipeline_multi_output_cut_parity(tmp_path, monkeypatch):
    """Output cutting at max_output_file_size interacts with the chunked
    writer (withheld final blocks): bytes must still match serially."""
    from toplingdb_tpu.compaction.picker import Compaction
    from toplingdb_tpu.db.table_cache import TableCache
    from toplingdb_tpu.env import default_env
    from toplingdb_tpu.ops.device_compaction import run_device_compaction
    from toplingdb_tpu.table.builder import TableOptions

    monkeypatch.setenv("TPULSM_HOST_SORT", "1")
    _enable_small_pipeline(monkeypatch, shards=5)
    env = default_env()
    dbdir = str(tmp_path)
    topts = TableOptions(block_size=512)
    metas = _build_runs(env, dbdir, 20_000, topts, seed=5)
    outs = {}
    for knob in ("0", "1"):
        monkeypatch.setenv("TPULSM_PIPELINE", knob)
        tc = TableCache(env, dbdir, ICMP, topts)
        c = Compaction(level=0, output_level=2, inputs=list(metas),
                       bottommost=True, max_output_file_size=64 * 1024)
        outs[knob], _ = run_device_compaction(
            env, dbdir, ICMP, c, tc, topts, [],
            new_file_number=_mk_alloc(3000 if knob == "0" else 4000),
            creation_time=7, device_name="cpu-jax",
        )
    assert len(outs["0"]) == len(outs["1"]) > 1, "want a multi-output job"
    for a, b in zip(_sst_bytes(env, dbdir, outs["0"]),
                    _sst_bytes(env, dbdir, outs["1"])):
        assert a == b


def test_pipeline_complex_groups_fall_back_byte_identical(tmp_path,
                                                          monkeypatch):
    """MERGE operands abort the pipeline mid-flight; the serial fallback
    must still produce the CPU path's exact bytes and leave no stray
    files from the aborted attempt."""
    import struct

    import toplingdb_tpu.db.filename as fn
    from toplingdb_tpu.compaction.compaction_job import run_compaction_to_tables
    from toplingdb_tpu.compaction.picker import Compaction
    from toplingdb_tpu.db.table_cache import TableCache
    from toplingdb_tpu.db.version_edit import FileMetaData
    from toplingdb_tpu.env import default_env
    from toplingdb_tpu.ops.device_compaction import run_device_compaction
    from toplingdb_tpu.table.builder import TableBuilder, TableOptions
    from toplingdb_tpu.utils.merge_operator import UInt64AddOperator

    monkeypatch.setenv("TPULSM_HOST_SORT", "1")
    _enable_small_pipeline(monkeypatch)
    env = default_env()
    dbdir = str(tmp_path)
    topts = TableOptions(block_size=512)
    rng = random.Random(11)
    metas = []
    seq = 1
    for fnum in (61, 62, 63):
        entries = []
        for _ in range(600):
            k = b"key%05d" % rng.randrange(700)
            r = rng.random()
            if r < 0.7:
                entries.append((make_internal_key(k, seq, ValueType.VALUE),
                                b"val%06d" % seq))
            else:
                entries.append((make_internal_key(k, seq, ValueType.MERGE),
                                struct.pack("<Q", seq % 97)))
            seq += 1
        entries.sort(key=lambda kv: ICMP.sort_key(kv[0]))
        w = env.new_writable_file(fn.table_file_name(dbdir, fnum))
        b = TableBuilder(w, ICMP, topts)
        for k, v in entries:
            b.add(k, v)
        props = b.finish()
        w.close()
        metas.append(FileMetaData(
            number=fnum,
            file_size=env.get_file_size(fn.table_file_name(dbdir, fnum)),
            smallest=b.smallest_key, largest=b.largest_key,
            smallest_seqno=props.smallest_seqno,
            largest_seqno=props.largest_seqno,
        ))
    op = UInt64AddOperator()

    def run(device, base):
        tc = TableCache(env, dbdir, ICMP, topts)
        c = Compaction(level=0, output_level=2, inputs=list(metas),
                       bottommost=True, max_output_file_size=1 << 62)
        if device:
            return run_device_compaction(
                env, dbdir, ICMP, c, tc, topts, [], merge_operator=op,
                new_file_number=_mk_alloc(base), creation_time=7,
                device_name="cpu-jax")
        return run_compaction_to_tables(
            env, dbdir, ICMP, c, tc, topts, [], merge_operator=op,
            new_file_number=_mk_alloc(base), creation_time=7)

    before = set(os.listdir(dbdir))
    out_cpu, _ = run(False, 5000)
    out_dev, _ = run(True, 6000)
    for a, b in zip(_sst_bytes(env, dbdir, out_cpu),
                    _sst_bytes(env, dbdir, out_dev)):
        assert a == b
    after = set(os.listdir(dbdir))
    expect = before | {f"{m.number:06d}.sst" for m in out_cpu + out_dev}
    assert after == expect, f"stray files: {sorted(after - expect)}"


def test_pipeline_zip_byte_parity(tmp_path, monkeypatch):
    """Zip-format outputs ride the pipeline: pipelined vs serial zip
    compaction produce byte-identical SSTs (snapshots + a surviving range
    tombstone included), and TPULSM_ZIP_PLANE=0 restores the serial
    fallback gate with the Python builder emitting the same bytes."""
    from toplingdb_tpu.env import default_env
    from toplingdb_tpu.table import format as fmt
    from toplingdb_tpu.table.builder import TableOptions
    from toplingdb_tpu.utils import codecs

    monkeypatch.setenv("TPULSM_HOST_SORT", "1")
    _enable_small_pipeline(monkeypatch)
    calls = _spy_pipeline(monkeypatch)

    env = default_env()
    dbdir = str(tmp_path)
    comp = fmt.ZSTD_COMPRESSION if codecs.available("zstd") \
        else fmt.NO_COMPRESSION
    topts = TableOptions(block_size=512)
    zip_topts = TableOptions(format="zip", compression=comp)
    n = 24_000
    metas = _build_runs(env, dbdir, n, topts, seed=3, tombstone_file=True)
    snapshots = [n // 3, 2 * n // 3]

    monkeypatch.setenv("TPULSM_PIPELINE", "0")
    out_serial, _ = _run_job(env, dbdir, metas, topts, zip_topts, 1000,
                             snapshots)
    assert not calls
    monkeypatch.setenv("TPULSM_PIPELINE", "1")
    out_pipe, _ = _run_job(env, dbdir, metas, topts, zip_topts, 2000,
                           snapshots)
    assert calls, "zip job did not ride the pipeline"

    assert len(out_serial) == len(out_pipe) >= 1
    for a, b in zip(_sst_bytes(env, dbdir, out_serial),
                    _sst_bytes(env, dbdir, out_pipe)):
        assert a == b, "pipelined zip SST bytes differ from serial"
    for a, b in zip(out_serial, out_pipe):
        assert (a.smallest, a.largest, a.num_entries) == \
            (b.smallest, b.largest, b.num_entries)

    # Knob off: the pipeline gate is back AND the pure-Python builder
    # reproduces the native kernels' bytes (the PR's writer oracle).
    calls.clear()
    monkeypatch.setenv("TPULSM_ZIP_PLANE", "0")
    out_off, _ = _run_job(env, dbdir, metas, topts, zip_topts, 3000,
                          snapshots)
    assert not calls, "TPULSM_ZIP_PLANE=0 must gate the pipeline"
    for a, b in zip(_sst_bytes(env, dbdir, out_serial),
                    _sst_bytes(env, dbdir, out_off)):
        assert a == b, "python zip builder bytes differ from native"


class _Cancel(BaseException):
    """Out-of-band cancellation (BaseException so no fallback retries)."""


def test_cancel_mid_pipeline_leaves_no_orphans(tmp_path, monkeypatch):
    """A cancellation landing in the compute stage mid-pipeline must tear
    down all stages and delete every partial output file."""
    from toplingdb_tpu.env import default_env
    from toplingdb_tpu.ops import compaction_kernels as ck
    from toplingdb_tpu.ops import pipeline as pl
    from toplingdb_tpu.table.builder import TableOptions

    monkeypatch.setenv("TPULSM_HOST_SORT", "1")
    _enable_small_pipeline(monkeypatch)
    env = default_env()
    dbdir = str(tmp_path)
    topts = TableOptions(block_size=512)
    metas = _build_runs(env, dbdir, 20_000, topts, seed=9)
    before = set(os.listdir(dbdir))

    orig = ck.host_fused_full
    hits = []

    def cancel_on_second(*a, **k):
        hits.append(1)
        if len(hits) >= 2:
            raise _Cancel("injected cancel")
        return orig(*a, **k)

    monkeypatch.setattr(ck, "host_fused_full", cancel_on_second)
    with pytest.raises(_Cancel):
        _run_job(env, dbdir, metas, topts, topts, 7000, [])
    monkeypatch.setattr(ck, "host_fused_full", orig)
    assert set(os.listdir(dbdir)) == before, "orphan outputs left behind"
    # The job still completes once the cancellation is gone.
    outs, _ = _run_job(env, dbdir, metas, topts, topts, 7100, [])
    assert outs and pl.pipeline_enabled()


def test_pipeline_prefetch_tickers(tmp_path, monkeypatch):
    """The compaction input scan exports FilePrefetchBuffer counters as
    PREFETCH_HITS / PREFETCH_MISSES tickers on the DB's statistics."""
    from toplingdb_tpu.db.db import DB
    from toplingdb_tpu.options import Options
    from toplingdb_tpu.table.builder import TableOptions
    from toplingdb_tpu.utils import statistics as st

    stats = st.Statistics()
    with DB.open(str(tmp_path / "db"),
                 Options(write_buffer_size=16 * 1024,
                         table_options=TableOptions(block_size=256),
                         statistics=stats)) as db:
        for i in range(4000):
            db.put(b"key%05d" % (i % 1200), b"val%06d" % i)
        db.flush()
        db.compact_range()
        db.wait_for_compactions()
    assert stats.get_ticker_count(st.PREFETCH_MISSES) > 0
    # Sequential block loads during the scan escalate into readahead
    # windows, so at least some reads must have been served from them.
    assert stats.get_ticker_count(st.PREFETCH_HITS) > 0


def test_phase_dict_overlap_reporting():
    """other_s clamps at 0; over-counted (overlapping) phases report an
    explicit pipeline_overlap_s instead of a free-text note."""
    from toplingdb_tpu.compaction.compaction_job import CompactionStats

    s = CompactionStats(work_time_usec=1_000_000, input_scan_usec=300_000,
                        host_compute_usec=500_000)
    d = s.phase_dict()
    assert d["other_s"] == pytest.approx(0.2)
    assert "pipeline_overlap_s" not in d

    s = CompactionStats(work_time_usec=1_000_000, input_scan_usec=800_000,
                        host_compute_usec=900_000,
                        encode_write_usec=700_000)
    d = s.phase_dict()
    assert d["other_s"] == 0.0
    assert d["pipeline_overlap_s"] == pytest.approx(1.4)
    assert all(not isinstance(v, str) for v in d.values())


def test_prefetch_buffer_pre_armed_window():
    """arm_immediately + initial_readahead fetch a full window on the very
    first read; sequential successors hit, a random read resets cleanly."""
    from toplingdb_tpu.env import MemEnv
    from toplingdb_tpu.table.prefetch import FilePrefetchBuffer

    env = MemEnv()
    w = env.new_writable_file("/pf")
    w.append(bytes(range(256)) * 1024)  # 256 KiB
    w.close()
    f = env.new_random_access_file("/pf")
    pf = FilePrefetchBuffer(f, max_readahead=64 * 1024,
                            initial_readahead=64 * 1024,
                            arm_immediately=True)
    assert pf.read(0, 4096) == bytes(range(256)) * 16
    assert (pf.hits, pf.misses) == (0, 1)
    for i in range(1, 16):
        pf.read(i * 4096, 4096)
    assert pf.hits == 15  # the rest of the 64 KiB window
    h, m = pf.hits, pf.misses
    pf.read(200 * 1024, 4096)  # random access: miss, state reset
    assert (pf.hits, pf.misses) == (h, m + 1)


@pytest.mark.parametrize("seed", [2])
def test_pipeline_soak_acknowledged_writes_survive(monkeypatch, seed):
    """Seeded soak with the pipeline forced on for every compaction
    (tests/test_fault_soak.py's model-checked shape): every acknowledged
    write survives flush+compaction cycles and a clean reopen."""
    from toplingdb_tpu.db.db import DB
    from toplingdb_tpu.options import Options

    _enable_small_pipeline(monkeypatch, shards=3)
    monkeypatch.setenv("TPULSM_HOST_SORT", "1")
    monkeypatch.setenv("TPULSM_PIPELINE", "1")
    rng = random.Random(seed)
    root = tempfile.mkdtemp(prefix=f"pipesoak{seed}_")
    d = root + "/db"
    model = {}
    try:
        db = DB.open(d, Options(write_buffer_size=8 * 1024,
                                level0_file_num_compaction_trigger=3))
        for cycle in range(5):
            for _ in range(rng.randrange(150, 400)):
                k = b"k%04d" % rng.randrange(600)
                if rng.random() < 0.12:
                    db.delete(k)
                    model.pop(k, None)
                else:
                    v = b"v%06d" % rng.randrange(10 ** 6)
                    db.put(k, v)
                    model[k] = v
            db.flush()
            if cycle % 2:
                db.compact_range()
            db.wait_for_compactions()
            bad = [k for k, v in model.items() if db.get(k) != v]
            assert not bad, (cycle, bad[:3])
            gone = [k for k in (b"k%04d" % i for i in range(600))
                    if k not in model and db.get(k) is not None]
            assert not gone, (cycle, gone[:3])
        db.close()
        with DB.open(d, Options()) as db2:
            bad = [k for k, v in model.items() if db2.get(k) != v]
            assert not bad, bad[:3]
    finally:
        shutil.rmtree(root, ignore_errors=True)
