"""On-disk format compatibility (the reference's
tools/check_format_compatible.sh role): tests/golden/dbv1 is a COMMITTED DB
directory written by the format as of the golden generation; every future
revision must still open it and read every record — SST (zlib blocks, bloom,
range-del meta), blob file, MANIFEST, OPTIONS, and a WAL tail needing
replay. If a format change is intentional, regenerate the golden dir in the
same commit and say so; silently failing here means the change orphans every
existing database.

The golden dir is regenerated (deterministically, frozen clock
creation_time=1753750000) by tests/golden/generate_dbv1.py.
"""

import os
import shutil

import pytest

GOLDEN = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                      "golden", "dbv1")


@pytest.fixture
def golden_copy(tmp_path):
    # Work on a copy: opening may roll the MANIFEST / write OPTIONS.
    dst = str(tmp_path / "dbv1")
    shutil.copytree(GOLDEN, dst)
    return dst


def test_golden_db_opens_and_reads(golden_copy):
    from toplingdb_tpu.db.db import DB
    from toplingdb_tpu.options import Options

    o = Options(enable_blob_files=True, min_blob_size=64)
    with DB.open(golden_copy, o) as db:
        for i in range(500):
            k = b"key%04d" % i
            if i == 100 or 200 <= i < 210:
                assert db.get(k) is None, k  # delete / delete_range
            else:
                assert db.get(k) == b"value-%04d" % i, k
        assert db.get(b"big") == b"B" * 500          # via the blob file
        assert db.get(b"wal-tail") == b"unflushed"   # WAL replay
        cf = db.get_column_family("meta")
        assert cf is not None
        assert db.get(b"mk", cf=cf) == b"mv"
        it = db.new_iterator()
        it.seek_to_first()
        n = sum(1 for _ in it.entries())
        assert n == 500 - 1 - 10 + 2  # keys - delete - range + big + wal-tail
        db.verify_checksum()


def test_golden_db_compacts_forward(golden_copy):
    """The current code can rewrite golden-format data with today's writers
    and still read it back."""
    from toplingdb_tpu.db.db import DB
    from toplingdb_tpu.options import Options

    o = Options(enable_blob_files=True, min_blob_size=64)
    with DB.open(golden_copy, o) as db:
        db.compact_range()
        assert db.get(b"key0000") == b"value-0000"
        assert db.get(b"big") == b"B" * 500
        assert db.get(b"key0205") is None
    with DB.open(golden_copy, o) as db:
        assert db.get(b"key0499") == b"value-0499"


def test_golden_options_loadable(golden_copy):
    from toplingdb_tpu.utils.config import load_latest_options

    loaded = load_latest_options(golden_copy)
    assert loaded is not None
    assert loaded.enable_blob_files is True


def test_golden_sst_dump_tool(golden_copy, capsys):
    """sst_dump reads golden SSTs standalone."""
    from toplingdb_tpu.tools import sst_dump

    ssts = sorted(f for f in os.listdir(golden_copy) if f.endswith(".sst"))
    assert ssts
    rc = sst_dump.main([
        f"--file={os.path.join(golden_copy, ssts[0])}", "--command=scan",
    ])
    assert rc == 0
    out = capsys.readouterr().out
    assert "entries" in out and "key" in out
