"""Fleet health plane (ISSUE 12): windowed histograms (ring rotation,
interpolated quantiles, exact merge), the SLO burn-rate engine (synthetic
latency shift + a REAL induced write stall), stats-history interval rows,
the dump-scheduler error ticker, shard health scores in the router view,
the /health–/slo–/cluster/health HTTP surface with fleet members, the
ReplicationServer scrape points, and the check_telemetry SLO/gauge lint.
"""

import json
import time
import urllib.request

import pytest

from toplingdb_tpu.db.db import DB
from toplingdb_tpu.options import Options
from toplingdb_tpu.utils import statistics as st
from toplingdb_tpu.utils import slo as slomod
from toplingdb_tpu.utils.listener import EventListener
from toplingdb_tpu.utils.slo import SLOEngine, SLOSpec
from toplingdb_tpu.utils.statistics import (Histogram, Statistics,
                                            WindowedHistogram)


def opts(**kw):
    kw.setdefault("create_if_missing", True)
    kw.setdefault("write_buffer_size", 1 << 20)
    return Options(**kw)


class FakeClock:
    def __init__(self, t=0.0):
        self.t = t

    def __call__(self):
        return self.t


# ---------------------------------------------------------------------------
# Histogram quantiles
# ---------------------------------------------------------------------------


def test_percentile_interpolation_and_clamping():
    h = Histogram()
    assert h.percentile(99) == 0.0
    assert h.observed_min == 0.0  # empty: never inf
    h.add(100)
    # One sample: every quantile reports the sample itself, not the
    # power-of-two bucket bound (128).
    assert h.percentile(50) == 100 and h.percentile(99) == 100
    for v in (10, 20, 40, 5000):
        h.add(v)
    assert h.observed_min == 10
    assert h.percentile(0.1) >= 10
    assert h.percentile(100) <= h.max == 5000
    assert h.percentile(50) <= h.percentile(90) <= h.percentile(99)


def test_fraction_above_interpolates():
    h = Histogram()
    for _ in range(100):
        h.add(100)
    assert h.fraction_above(5000) == 0.0
    assert h.fraction_above(1) == 1.0
    # threshold inside the occupied [64, 128) bucket: partial credit
    assert 0.0 < h.fraction_above(100) < 1.0


def test_histogram_merge_and_dict_roundtrip():
    a, b = Histogram(), Histogram()
    for v in (1, 2, 300):
        a.add(v)
    for v in (4_000_000, 7):
        b.add(v)
    m = Histogram.from_dict(a.to_dict()).merge(Histogram.from_dict(
        b.to_dict()))
    assert m.count == 5 and m.sum == a.sum + b.sum
    assert m.min == 1 and m.max == 4_000_000
    one = Histogram()
    for v in (1, 2, 300, 4_000_000, 7):
        one.add(v)
    assert m.buckets == one.buckets


# ---------------------------------------------------------------------------
# Windowed histograms
# ---------------------------------------------------------------------------


def test_windowed_p99_tracks_latency_shift_cumulative_misses():
    """The tentpole behavior: after a long healthy run, a latency shift
    shows in the windowed p99 within one window while the cumulative p99
    stays diluted below the alert threshold."""
    clk = FakeClock()
    w = WindowedHistogram(window_sec=60.0, intervals=6, clock=clk)
    for _ in range(50_000):
        w.add(100)  # a long healthy history of ~100us gets
    clk.t = 70.0
    w.windowed()  # reader-side rotation past the healthy epoch
    for _ in range(400):
        w.add(20_000)  # the regression: 20ms gets
    recent = w.windowed()
    assert recent.count == 400
    assert recent.percentile(99) >= 10_000
    # 400 / 50_400 = 0.8% slow: lifetime p99 never crosses the threshold
    assert w.percentile(99) < 1_000
    assert w.count == 50_400


def test_windowed_ring_expiry_and_lifetime_retention():
    clk = FakeClock()
    w = WindowedHistogram(window_sec=60.0, intervals=6, clock=clk)
    for _ in range(100):
        w.add(100)
    clk.t = 35.0
    w.windowed()
    for _ in range(100):
        w.add(20_000)
    assert w.windowed().count == 200  # both batches inside the window
    clk.t = 75.0  # epoch 7: the t=0 batch expired, the t=35 one lives
    win = w.windowed()
    assert win.count == 100 and win.min == 20_000
    clk.t = 500.0  # everything expired from the window...
    assert w.windowed().count == 0
    # ...but the lifetime series retains every sample exactly
    assert w.count == 200 and w.min == 100 and w.max == 20_000
    assert sum(w.buckets) == 200


def test_windowed_merge_folds_into_lifetime_not_window():
    clk = FakeClock()
    w = WindowedHistogram(window_sec=60.0, intervals=6, clock=clk)
    other = Histogram()
    for _ in range(50):
        other.add(7)
    w.merge(other)  # merged-in data is historical
    assert w.count == 50 and w.windowed().count == 0


def test_windowed_merge_parity_across_members():
    """The aggregator invariant: merging two members' windowed dumps
    equals one histogram fed both streams."""
    clk = FakeClock()
    a = WindowedHistogram(window_sec=60.0, intervals=6, clock=clk)
    b = WindowedHistogram(window_sec=60.0, intervals=6, clock=clk)
    one = Histogram()
    for i in range(1000):
        v = (i % 97) + 1
        (a if i % 2 else b).add(v)
        one.add(v)
    merged = Histogram.from_dict(a.windowed().to_dict()).merge(
        Histogram.from_dict(b.windowed().to_dict()))
    assert merged.count == one.count == 1000
    assert merged.buckets == one.buckets
    assert merged.sum == one.sum


def test_statistics_windowed_wiring_and_prometheus_recent():
    s = Statistics(histogram_window_sec=60.0)
    for v in (100, 200, 400):
        s.record_in_histogram(st.DB_GET_MICROS, v)
    text = s.to_prometheus()
    assert "_recent" in text and 'quantile="0.99"' in text
    # window disabled -> plain histograms, no _recent series
    s0 = Statistics(histogram_window_sec=0)
    s0.record_in_histogram(st.DB_GET_MICROS, 100)
    assert "_recent" not in s0.to_prometheus()
    # re-keying rebuilds only empty histograms
    s0.set_histogram_window(30.0, 3)
    assert isinstance(s0._histograms[st.BYTES_PER_READ], WindowedHistogram)
    assert not isinstance(s0._histograms[st.DB_GET_MICROS],
                          WindowedHistogram)  # populated: kept


# ---------------------------------------------------------------------------
# Stats history interval rows + dump scheduler errors
# ---------------------------------------------------------------------------


def test_stats_history_interval_histogram_rows():
    from toplingdb_tpu.utils.stats_history import StatsHistory

    s = Statistics(histogram_window_sec=60.0)
    sh = StatsHistory(s, max_samples=10)
    s.record_in_histogram(st.DB_WRITE_MICROS, 100)
    s.record_in_histogram(st.DB_WRITE_MICROS, 300)
    sh.snapshot()
    s.record_in_histogram(st.DB_WRITE_MICROS, 900)
    sh.snapshot()
    rows = sh.series()
    assert len(rows) == 2
    first, last = rows[0]["histograms"], rows[-1]["histograms"]
    assert first[st.DB_WRITE_MICROS]["count"] == 2
    assert first[st.DB_WRITE_MICROS]["sum"] == 400
    assert last[st.DB_WRITE_MICROS]["count"] == 1
    assert last[st.DB_WRITE_MICROS]["sum"] == 900
    assert last[st.DB_WRITE_MICROS]["max"] >= 900


def test_stats_dump_scheduler_error_ticker_and_stop():
    from toplingdb_tpu.utils.stats_history import (StatsDumpScheduler,
                                                   StatsHistory)

    s = Statistics()
    sh = StatsHistory(s, max_samples=50)
    boom = {"n": 0}

    def on_snapshot():
        boom["n"] += 1
        raise RuntimeError("dump line failed")

    sched = StatsDumpScheduler(sh, period_sec=0.01, on_snapshot=on_snapshot)
    deadline = time.time() + 5.0
    while boom["n"] < 3 and time.time() < deadline:
        time.sleep(0.01)
    assert sched.stop() is True  # clean join reported
    assert boom["n"] >= 3
    assert sched.errors == boom["n"]
    assert s.get_ticker_count(st.STATS_DUMP_ERRORS) == sched.errors
    assert sh.last_sample() is not None  # snapshots kept flowing


# ---------------------------------------------------------------------------
# SLO engine
# ---------------------------------------------------------------------------


def test_slo_spec_validation():
    with pytest.raises(ValueError):
        SLOSpec(name="x", kind="bogus")
    with pytest.raises(ValueError):
        SLOSpec(name="x", objective=1.0)
    with pytest.raises(ValueError):
        # fraction needs BOTH the bad and the total ticker sets
        SLOSpec(name="x", kind="fraction", bad_tickers=(st.STALL_MICROS,))
    with pytest.raises(ValueError):
        SLOEngine(Statistics(), [SLOSpec(name="a"), SLOSpec(name="a")])


def test_slo_burn_rate_fires_and_resolves_with_listener():
    clk = FakeClock()
    s = Statistics(histogram_window_sec=60.0)
    seen = []

    class L(EventListener):
        def on_slo_alert(self, db, info):
            seen.append(info)

    eng = SLOEngine(
        s, [SLOSpec(name="get-p99", kind="latency", objective=0.99,
                    histogram=st.DB_GET_MICROS, threshold_usec=10_000,
                    window_fast_sec=30.0, window_slow_sec=150.0)],
        db_name="t", listeners=[L()], clock=clk)
    for _ in range(200):
        s.record_in_histogram(st.DB_GET_MICROS, 100)
    for _ in range(3):
        clk.t += 10.0
        eng.evaluate()
    assert not eng.status()["specs"]["get-p99"]["firing"]
    assert eng.health() == slomod.HEALTH_GREEN
    # 20% of gets go slow: burn rate ~20x the 1% budget
    for i in range(500):
        s.record_in_histogram(
            st.DB_GET_MICROS, 50_000 if i % 5 == 0 else 100)
    fired_after = None
    for step in range(3):  # acceptance: fires within 3 windows
        clk.t += 10.0
        eng.evaluate()
        if eng.status()["specs"]["get-p99"]["firing"]:
            fired_after = step + 1
            break
    assert fired_after is not None and fired_after <= 3
    assert eng.health() == slomod.HEALTH_UNHEALTHY
    assert [a.state for a in seen] == ["firing"]
    assert seen[0].slo_name == "get-p99" and seen[0].db_name == "t"
    assert seen[0].burn_rate_fast >= 6.0
    # recovery: fast burn falls below the fast threshold -> resolved
    for _ in range(20_000):
        s.record_in_histogram(st.DB_GET_MICROS, 100)
    for _ in range(30):
        clk.t += 10.0
        eng.evaluate()
        if not eng.status()["specs"]["get-p99"]["firing"]:
            break
    assert not eng.status()["specs"]["get-p99"]["firing"]
    assert [a.state for a in seen] == ["firing", "resolved"]
    assert s.get_ticker_count(st.SLO_ALERTS_FIRED) == 1
    assert s.get_ticker_count(st.SLO_ALERTS_RESOLVED) == 1
    assert s.get_ticker_count(st.SLO_EVALUATIONS) > 0
    assert "get-p99" in eng.last_alerts()


def test_slo_alert_under_induced_write_stall(tmp_path):
    """The acceptance scenario on a REAL DB: level0_slowdown_writes_trigger=1
    makes every post-flush write ride the delay ramp; the stall SLO's
    burn rate crosses its thresholds within 3 evaluation passes."""
    stats = Statistics(histogram_window_sec=60.0)
    db = DB.open(str(tmp_path / "d"),
                 opts(statistics=stats,
                      level0_slowdown_writes_trigger=1,
                      level0_stop_writes_trigger=100,
                      level0_file_num_compaction_trigger=64,
                      slo_specs=(SLOSpec(name="stall", kind="stall",
                                         objective=0.999),),
                      slo_window_sec=5.0))
    try:
        eng = db.slo_engine
        assert eng is not None
        eng.evaluate()  # baseline sample, everything green
        assert eng.health() == slomod.HEALTH_GREEN
        db.put(b"a", b"1")
        db.flush()
        db.put(b"b", b"2")
        db.flush()
        for i in range(4):
            db.put(b"c%d" % i, b"3")  # each write sleeps on the ramp
        assert stats.get_ticker_count(st.STALL_MICROS) > 0
        fired = False
        for _ in range(3):
            time.sleep(0.02)
            eng.evaluate()
            if eng.status()["specs"]["stall"]["firing"]:
                fired = True
                break
        assert fired
        assert eng.health() == slomod.HEALTH_UNHEALTHY
        # and the doc every fleet endpoint serves carries the verdict
        doc = slomod.health_doc(db, "d")
        assert doc["health"] == slomod.HEALTH_UNHEALTHY
        assert doc["slo"]["specs"]["stall"]["firing"]
        assert st.DB_WRITE_MICROS in doc["histograms"]
    finally:
        db.close()


def test_health_score_rubric():
    assert slomod.health_score() == slomod.HEALTH_GREEN
    assert slomod.health_score(stall_state="delayed") \
        == slomod.HEALTH_DEGRADED
    assert slomod.health_score(stall_state="stopped") \
        == slomod.HEALTH_UNHEALTHY
    assert slomod.health_score(breakers_open=1) == slomod.HEALTH_DEGRADED
    assert slomod.health_score(lag_exceeded=True) == slomod.HEALTH_DEGRADED
    # worst input wins
    assert slomod.health_score(stall_state="delayed",
                               slo_health=slomod.HEALTH_UNHEALTHY) \
        == slomod.HEALTH_UNHEALTHY
    assert slomod.health_num(slomod.HEALTH_UNHEALTHY) == 2


# ---------------------------------------------------------------------------
# Shard health in the router view
# ---------------------------------------------------------------------------


def test_shard_health_stalled_shard_flips_while_siblings_stay_green(
        tmp_path):
    from toplingdb_tpu.sharding import open_local_cluster

    def factory(name):
        return opts(statistics=Statistics(),
                    level0_slowdown_writes_trigger=1,
                    level0_stop_writes_trigger=100,
                    level0_file_num_compaction_trigger=64)

    router = open_local_cluster(
        str(tmp_path), [("a", None, b"m"), ("b", b"m", None)],
        options_factory=factory, statistics=Statistics())
    try:
        rows = {r["name"]: r for r in router.status()["shards"]}
        assert rows["a"]["health"] == slomod.HEALTH_GREEN
        assert rows["b"]["health"] == slomod.HEALTH_GREEN
        # stall ONLY shard a's primary
        pa = router._servings["a"].primary
        pa.put(b"a1", b"1")
        pa.flush()
        pa.put(b"a2", b"2")
        pa.flush()
        assert pa.write_stall_state()["state"] == "delayed"
        rows = {r["name"]: r for r in router.status()["shards"]}
        assert rows["a"]["health"] == slomod.HEALTH_DEGRADED
        assert rows["b"]["health"] == slomod.HEALTH_GREEN  # sibling green
        assert "breakers_open" in rows["a"]
    finally:
        router.close()


# ---------------------------------------------------------------------------
# HTTP surface: /slo, /health, /metrics gauges, /cluster/health + fleet
# ---------------------------------------------------------------------------


def _get_json(url):
    with urllib.request.urlopen(url, timeout=10) as r:
        return json.loads(r.read())


def test_http_slo_health_and_cluster_fleet(tmp_path):
    from toplingdb_tpu.replication.log_shipper import ReplicationServer
    from toplingdb_tpu.utils.config import SidePluginRepo

    stats = Statistics(histogram_window_sec=60.0)
    db = DB.open(str(tmp_path / "d"),
                 opts(statistics=stats,
                      slo_specs=({"name": "get-p99", "kind": "latency",
                                  "histogram": st.DB_GET_MICROS,
                                  "objective": 0.99,
                                  "threshold_usec": 10_000},),))
    member = DB.open(str(tmp_path / "m"),
                     opts(statistics=Statistics(histogram_window_sec=60.0)))
    rsrv = ReplicationServer(member)
    rport = rsrv.start()
    repo = SidePluginRepo()
    repo.attach_db("d", db)
    repo.attach_fleet_member(
        "member", f"http://127.0.0.1:{rport}/replication/health")
    repo.attach_fleet_member("ghost", "http://127.0.0.1:9/health/x")
    port = repo.start_http()
    base = f"http://127.0.0.1:{port}"
    try:
        db.put(b"k", b"v")
        for _ in range(20):
            db.get(b"k")
        member.put(b"mk", b"mv")
        member.get(b"mk")

        out = _get_json(f"{base}/slo/d?evaluate=1")
        assert out["health"] == slomod.HEALTH_GREEN
        assert out["specs"]["get-p99"]["burn_rate_fast"] >= 0.0

        doc = _get_json(f"{base}/health/d")
        assert doc["name"] == "d" and doc["role"] == "primary"
        row = doc["histograms"][st.DB_GET_MICROS]
        assert row["cumulative"]["count"] == 20 and "recent" in row

        # the member's own scrape points
        mdoc = _get_json(
            f"http://127.0.0.1:{rport}/replication/health")
        assert mdoc["role"] == "primary" and "replication" in mdoc
        with urllib.request.urlopen(
                f"http://127.0.0.1:{rport}/metrics", timeout=10) as r:
            mtext = r.read().decode()
        assert "tpulsm_" in mtext and 'db="m"' in mtext

        cluster = _get_json(f"{base}/cluster/health")
        assert cluster["health"] == slomod.HEALTH_UNHEALTHY  # the ghost
        assert cluster["n_unreachable"] == 1
        names = {m["name"]: m for m in cluster["members"]}
        assert names["ghost"]["health"] == "unreachable"
        # the member self-reports its identity (db basename); the
        # registration alias only names unreachable rows
        assert names["m"]["role"] == "primary"
        assert names["d"]["health"] == slomod.HEALTH_GREEN
        # merge parity: fleet cumulative gets == local + member
        gets = cluster["histograms"][st.DB_GET_MICROS]["cumulative"]
        assert gets["count"] == 21

        with urllib.request.urlopen(f"{base}/metrics", timeout=10) as r:
            text = r.read().decode()
        assert 'tpulsm_slo_firing{db="d",slo="get-p99"} 0' in text
        assert 'tpulsm_slo_health{db="d"} 0' in text
        assert 'tpulsm_fleet_members{repo="fleet"} 2' in text
        assert 'tpulsm_fleet_members_unreachable{repo="fleet"} 1' in text
        assert "_recent" in text  # windowed series exposed
    finally:
        repo.stop_http()
        rsrv.stop()
        member.close()
        db.close()


# ---------------------------------------------------------------------------
# Fleet aggregator units + CLI rendering
# ---------------------------------------------------------------------------


def _doc(name, health, n_gets):
    h = Histogram()
    for _ in range(n_gets):
        h.add(100)
    return {"name": name, "role": "primary", "health": health,
            "stall": {"state": "none"},
            "histograms": {st.DB_GET_MICROS: {
                "cumulative": h.to_dict(), "recent": h.to_dict(),
                "window_sec": 60.0}},
            "slo": {"specs": {"s": {"firing": health != "green"}}}}


def test_fleet_aggregator_merge_and_summarize():
    from toplingdb_tpu.tools.fleet_health import (FleetHealthAggregator,
                                                  render)

    docs = [_doc("a", "green", 10), _doc("b", "degraded", 5)]
    merged = FleetHealthAggregator.merge_histograms(docs)
    assert merged[st.DB_GET_MICROS]["cumulative"].count == 15
    summary = FleetHealthAggregator.summarize(docs, {"c": "boom"})
    assert summary["health"] == slomod.HEALTH_UNHEALTHY  # unreachable
    assert summary["n_members"] == 2 and summary["n_unreachable"] == 1
    rows = {m["name"]: m for m in summary["members"]}
    assert rows["b"]["firing"] == ["s"]
    assert rows["c"]["health"] == "unreachable"
    assert summary["histograms"][st.DB_GET_MICROS]["cumulative"][
        "count"] == 15
    text = render(summary)
    assert "fleet health: unhealthy" in text and "MEMBER" in text


# ---------------------------------------------------------------------------
# check_telemetry: gauge + SLO lint
# ---------------------------------------------------------------------------


def test_check_telemetry_flags_bad_gauges_and_slo_specs(tmp_path):
    from toplingdb_tpu.tools import check_telemetry as ct

    bad = tmp_path / "bad.py"
    bad.write_text(
        "g(\"not_a_gauge\", 1)\n"
        "SLOSpec(name=\"x\", kind=\"bogus\")\n"
        "SLOSpec(name=\"y\", histogram=\"no.such.hist\")\n")
    stat_values, stat_attrs = ct.declared_stat_names()
    out = ct.check_file(str(bad), stat_values, stat_attrs, set(),
                        gauge_names={"memtable_bytes"},
                        slo_kinds=set(slomod.KINDS))
    assert len(out) == 3
    assert any("not_a_gauge" in v for v in out)
    assert any("bogus" in v for v in out)
    assert any("no.such.hist" in v for v in out)
    good = tmp_path / "good.py"
    good.write_text(
        "g(\"memtable_bytes\", 1)\n"
        "SLOSpec(name=\"x\", kind=\"latency\", histogram=\"db.get.micros\")\n")
    assert ct.check_file(str(good), stat_values, stat_attrs, set(),
                         gauge_names={"memtable_bytes"},
                         slo_kinds=set(slomod.KINDS)) == []


def test_check_telemetry_tree_is_clean():
    from toplingdb_tpu.tools.check_telemetry import run

    assert run() == []
