import pytest

from toplingdb_tpu.db.db import DB
from toplingdb_tpu.options import Options, ReadOptions
from toplingdb_tpu.utils.merge_operator import StringAppendOperator


def opts(**kw):
    kw.setdefault("write_buffer_size", 16 * 1024)
    return Options(**kw)


@pytest.fixture
def db(tmp_db_path):
    with DB.open(tmp_db_path, opts(merge_operator=StringAppendOperator())) as d:
        yield d


def fill(db, n=50, prefix=b"key"):
    for i in range(n):
        db.put(prefix + b"%04d" % i, b"v%04d" % i)


def test_forward_scan(db):
    fill(db)
    it = db.new_iterator()
    it.seek_to_first()
    got = list(it.entries())
    assert got == [(b"key%04d" % i, b"v%04d" % i) for i in range(50)]


def test_scan_across_memtable_and_sst(db):
    fill(db, 30)
    db.flush()
    for i in range(30, 60):
        db.put(b"key%04d" % i, b"v%04d" % i)
    it = db.new_iterator()
    it.seek_to_first()
    assert len(list(it.entries())) == 60


def test_newest_version_wins(db):
    db.put(b"k", b"old")
    db.flush()
    db.put(b"k", b"new")
    it = db.new_iterator()
    it.seek_to_first()
    assert list(it.entries()) == [(b"k", b"new")]


def test_deleted_keys_hidden(db):
    fill(db, 10)
    db.delete(b"key0005")
    it = db.new_iterator()
    it.seek_to_first()
    keys = [k for k, _ in it.entries()]
    assert b"key0005" not in keys
    assert len(keys) == 9


def test_seek_and_bounds(db):
    fill(db, 20)
    it = db.new_iterator(ReadOptions(
        iterate_lower_bound=b"key0005", iterate_upper_bound=b"key0015"
    ))
    it.seek_to_first()
    keys = [k for k, _ in it.entries()]
    assert keys[0] == b"key0005"
    assert keys[-1] == b"key0014"
    it.seek(b"key0000")
    assert it.key() == b"key0005"  # clamped to lower bound


def test_backward_scan(db):
    fill(db, 20)
    db.delete(b"key0010")
    it = db.new_iterator()
    it.seek_to_last()
    got = []
    while it.valid():
        got.append(it.key())
        it.prev()
    expect = [b"key%04d" % i for i in reversed(range(20)) if i != 10]
    assert got == expect


def test_seek_for_prev(db):
    fill(db, 10)
    it = db.new_iterator()
    it.seek_for_prev(b"key00055")
    assert it.valid() and it.key() == b"key0005"


def test_iterator_snapshot_consistency(db):
    fill(db, 10)
    it = db.new_iterator()
    db.put(b"key0099", b"late")
    it.seek_to_first()
    keys = [k for k, _ in it.entries()]
    assert b"key0099" not in keys  # iterator sees its creation snapshot


def test_merge_in_iterator(db):
    db.put(b"m", b"base")
    db.merge(b"m", b"x")
    db.flush()
    db.merge(b"m", b"y")
    it = db.new_iterator()
    it.seek_to_first()
    assert list(it.entries()) == [(b"m", b"base,x,y")]


def test_range_del_in_iterator(db):
    fill(db, 30)
    db.flush()
    db.delete_range(b"key0010", b"key0020")
    it = db.new_iterator()
    it.seek_to_first()
    keys = [k for k, _ in it.entries()]
    assert len(keys) == 20
    assert b"key0010" not in keys and b"key0019" not in keys
    # Backward too.
    it.seek_to_last()
    back = []
    while it.valid():
        back.append(it.key())
        it.prev()
    assert back == list(reversed(keys))
