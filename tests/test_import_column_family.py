"""Export/Import column family — reference Checkpoint::ExportColumnFamily +
DB::CreateColumnFamilyWithImport (db/import_column_family_job.cc)."""

import pytest

from toplingdb_tpu.db.db import DB
from toplingdb_tpu.options import Options, ReadOptions
from toplingdb_tpu.utilities.checkpoint import (
    ExportImportFilesMetaData,
    export_column_family,
)
from toplingdb_tpu.utils.status import InvalidArgument


def _filled_db(path, n=500, compact=True):
    db = DB.open(str(path), Options(write_buffer_size=32 * 1024))
    for i in range(n):
        db.put(b"key%05d" % i, b"val%05d" % i)
    db.flush()
    for i in range(0, n, 3):
        db.put(b"key%05d" % i, b"upd%05d" % i)
    db.flush()
    if compact:
        db.compact_range()
    return db


def test_export_import_roundtrip(tmp_path):
    src = _filled_db(tmp_path / "src")
    meta = export_column_family(src, None, str(tmp_path / "exp"))
    assert meta.files and meta.db_comparator_name
    src.close()

    dst = DB.open(str(tmp_path / "dst"), Options())
    dst.put(b"own", b"data")
    h = dst.create_column_family_with_import("imported", str(tmp_path / "exp"))
    # imported data readable in the new CF
    assert dst.get(b"key00003", cf=h) == b"upd00003"
    assert dst.get(b"key00001", cf=h) == b"val00001"
    assert dst.get(b"own", cf=h) is None
    assert dst.get(b"own") == b"data"
    # full scan count
    it = dst.new_iterator(ReadOptions(), cf=h)
    it.seek_to_first()
    assert sum(1 for _ in it.entries()) == 500
    # survives reopen
    dst.close()
    dst = DB.open(str(tmp_path / "dst"), Options())
    h2 = dst.get_column_family("imported")
    assert h2 is not None
    assert dst.get(b"key00042", cf=h2) == b"upd00042"
    dst.close()


def test_import_with_explicit_metadata_and_move(tmp_path):
    src = _filled_db(tmp_path / "src", n=50)
    meta = export_column_family(src, None, str(tmp_path / "exp"))
    src.close()
    dst = DB.open(str(tmp_path / "dst"), Options())
    h = dst.create_column_family_with_import(
        "cf2", str(tmp_path / "exp"), metadata=meta, move_files=True
    )
    assert dst.get(b"key00049", cf=h) == b"val00049"
    # exported SSTs were consumed
    left = [p for p in (tmp_path / "exp").iterdir() if p.suffix == ".sst"]
    assert not left
    dst.close()


def test_import_multi_level_layout(tmp_path):
    # No final compact: levels 0 + compacted level both present
    src = _filled_db(tmp_path / "src", n=300, compact=False)
    meta = export_column_family(src, None, str(tmp_path / "exp"))
    levels = {f.level for f in meta.files}
    src.close()
    dst = DB.open(str(tmp_path / "dst"), Options())
    h = dst.create_column_family_with_import("cf", str(tmp_path / "exp"))
    for i in range(300):
        want = b"upd%05d" % i if i % 3 == 0 else b"val%05d" % i
        assert dst.get(b"key%05d" % i, cf=h) == want
    st = dst.versions.column_families[h.id]
    assert {lvl for lvl, _ in st.current.all_files()} == levels
    dst.close()


def test_import_comparator_mismatch(tmp_path):
    src = _filled_db(tmp_path / "src", n=20)
    export_column_family(src, None, str(tmp_path / "exp"))
    src.close()
    meta = None
    from toplingdb_tpu.db.dbformat import REVERSE_BYTEWISE

    dst = DB.open(str(tmp_path / "dst"), Options(comparator=REVERSE_BYTEWISE))
    with pytest.raises(InvalidArgument):
        dst.create_column_family_with_import("cf", str(tmp_path / "exp"), meta)
    # failed import leaves no half-created CF behind
    assert dst.get_column_family("cf") is None
    dst.close()


def test_import_seqno_visibility(tmp_path):
    """Imported files carry seqnos from the source DB, which can be far
    ahead of the destination's — they must still be visible."""
    src = _filled_db(tmp_path / "src", n=200)  # plenty of seqnos
    export_column_family(src, None, str(tmp_path / "exp"))
    src.close()
    dst = DB.open(str(tmp_path / "dst"), Options())  # fresh: last_seq ~ 0
    h = dst.create_column_family_with_import("cf", str(tmp_path / "exp"))
    assert dst.get(b"key00000", cf=h) == b"upd00000"
    # new writes in the dest still supersede imported data
    dst.put(b"key00000", b"newer", cf=h)
    assert dst.get(b"key00000", cf=h) == b"newer"
    dst.close()


def test_export_dir_must_be_empty(tmp_path):
    src = _filled_db(tmp_path / "src", n=10)
    (tmp_path / "exp").mkdir()
    (tmp_path / "exp" / "junk").write_text("x")
    with pytest.raises(InvalidArgument):
        export_column_family(src, None, str(tmp_path / "exp"))
    src.close()


def test_metadata_file_roundtrip(tmp_path):
    src = _filled_db(tmp_path / "src", n=30)
    meta = export_column_family(src, None, str(tmp_path / "exp"))
    loaded = ExportImportFilesMetaData.load(str(tmp_path / "exp"), src.env)
    assert loaded.db_comparator_name == meta.db_comparator_name
    assert [f.name for f in loaded.files] == [f.name for f in meta.files]
    assert loaded.files[0].smallest == meta.files[0].smallest
    src.close()
