"""End-to-end compaction tests (shaped after reference db_compaction_test.cc)."""

import struct

import pytest

from toplingdb_tpu.db.db import DB
from toplingdb_tpu.options import Options, ReadOptions
from toplingdb_tpu.utils.compaction_filter import CompactionFilter, Decision
from toplingdb_tpu.utils.merge_operator import UInt64AddOperator


def opts(**kw):
    kw.setdefault("write_buffer_size", 8 * 1024)
    kw.setdefault("target_file_size_base", 16 * 1024)
    kw.setdefault("max_bytes_for_level_base", 64 * 1024)
    return Options(**kw)


def fill(db, n, fmt_=b"key%06d", val=b"v%08d", mod=None):
    for i in range(n):
        k = fmt_ % (i % mod if mod else i)
        db.put(k, val % i)


def test_auto_leveled_compaction_moves_data_down(tmp_db_path):
    with DB.open(tmp_db_path, opts()) as db:
        fill(db, 6000)
        db.flush()
        db.wait_for_compactions()
        v = db.versions.current
        deeper = sum(len(v.files[l]) for l in range(1, v.num_levels))
        assert deeper > 0, db.get_property("tpulsm.stats")
        for i in range(0, 6000, 501):
            assert db.get(b"key%06d" % i) == b"v%08d" % i


def test_overwrites_are_garbage_collected(tmp_db_path):
    with DB.open(tmp_db_path, opts()) as db:
        fill(db, 9000, mod=1000)  # 9x overwrites
        db.flush()
        db.compact_range()
        v = db.versions.current
        total_entries = sum(f.num_entries for _, f in v.all_files())
        assert total_entries == 1000  # exactly one version per key survives
        for k in range(0, 1000, 97):
            last = max(i for i in range(k, 9000, 1000))
            assert db.get(b"key%06d" % k) == b"v%08d" % last


def test_deletes_reclaimed_at_bottommost(tmp_db_path):
    with DB.open(tmp_db_path, opts()) as db:
        fill(db, 1000)
        for i in range(0, 1000, 2):
            db.delete(b"key%06d" % i)
        db.flush()
        db.compact_range()
        v = db.versions.current
        total = sum(f.num_entries for _, f in v.all_files())
        assert total == 500  # tombstones and dead values gone
        assert db.get(b"key%06d" % 0) is None
        assert db.get(b"key%06d" % 1) == b"v%08d" % 1


def test_snapshot_survives_compaction(tmp_db_path):
    with DB.open(tmp_db_path, opts()) as db:
        db.put(b"k", b"old")
        snap = db.get_snapshot()
        db.put(b"k", b"new")
        db.delete(b"dead")
        db.flush()
        db.compact_range()
        assert db.get(b"k", ReadOptions(snapshot=snap)) == b"old"
        assert db.get(b"k") == b"new"
        snap.release()
        db.compact_range()
        assert db.get(b"k") == b"new"


def test_merge_operands_fold_in_compaction(tmp_db_path):
    with DB.open(tmp_db_path, opts(merge_operator=UInt64AddOperator())) as db:
        for _ in range(10):
            db.merge(b"ctr", struct.pack("<Q", 1))
        db.flush()
        db.compact_range()
        v = db.versions.current
        total = sum(f.num_entries for _, f in v.all_files())
        assert total == 1  # chain folded to a single record
        assert struct.unpack("<Q", db.get(b"ctr"))[0] == 10


def test_delete_range_through_compaction(tmp_db_path):
    with DB.open(tmp_db_path, opts()) as db:
        fill(db, 2000)
        db.delete_range(b"key000500", b"key001000")
        db.flush()
        db.compact_range()
        assert db.get(b"key000499") == b"v%08d" % 499
        assert db.get(b"key000500") is None
        assert db.get(b"key000999") is None
        assert db.get(b"key001000") == b"v%08d" % 1000
        v = db.versions.current
        total = sum(f.num_entries for _, f in v.all_files())
        assert total == 1500  # covered keys physically gone at bottommost


def test_compaction_filter_applied(tmp_db_path):
    class DropPrefix(CompactionFilter):
        def name(self):
            return "DropPrefix"

        def filter(self, level, key, value):
            if key.startswith(b"tmp_"):
                return Decision.REMOVE, None
            return Decision.KEEP, None

    with DB.open(tmp_db_path, opts(compaction_filter=DropPrefix())) as db:
        db.put(b"keep_1", b"v")
        db.put(b"tmp_1", b"v")
        db.put(b"tmp_2", b"v")
        db.flush()
        db.compact_range()
        assert db.get(b"keep_1") == b"v"
        assert db.get(b"tmp_1") is None
        assert db.get(b"tmp_2") is None


def test_universal_compaction_correctness(tmp_db_path):
    with DB.open(tmp_db_path, opts(compaction_style="universal",
                                   level0_file_num_compaction_trigger=3)) as db:
        for round_ in range(6):
            for i in range(300):
                db.put(b"key%04d" % i, b"r%d" % round_)
            db.flush()
        db.wait_for_compactions()
        for i in range(300):
            assert db.get(b"key%04d" % i) == b"r5"
        it = db.new_iterator()
        it.seek_to_first()
        assert sum(1 for _ in it.entries()) == 300


def test_fifo_compaction_drops_oldest(tmp_db_path):
    with DB.open(tmp_db_path, opts(
        compaction_style="fifo", fifo_max_table_files_size=40 * 1024,
        write_buffer_size=8 * 1024, disable_auto_compactions=False,
    )) as db:
        for i in range(4000):
            db.put(b"key%06d" % i, b"x" * 40)
        db.flush()
        db.wait_for_compactions()
        v = db.versions.current
        assert v.total_bytes(0) <= 60 * 1024  # bounded
        # Newest keys still present.
        assert db.get(b"key%06d" % 3999) is not None


def test_compacted_db_reopens_correctly(tmp_db_path):
    with DB.open(tmp_db_path, opts()) as db:
        fill(db, 3000, mod=500)
        db.flush()
        db.compact_range()
    with DB.open(tmp_db_path, opts()) as db:
        for k in range(0, 500, 41):
            last = max(i for i in range(k, 3000, 500))
            assert db.get(b"key%06d" % k) == b"v%08d" % last


def test_l0_to_l1_trigger(tmp_db_path):
    with DB.open(tmp_db_path, opts(
        level0_file_num_compaction_trigger=4, disable_auto_compactions=True
    )) as db:
        for r in range(5):
            for i in range(100):
                db.put(b"k%04d" % i, b"r%d" % r)
            db.flush()
        assert len(db.versions.current.files[0]) == 5
        db.options.disable_auto_compactions = False
        db._maybe_schedule_compaction()
        db.wait_for_compactions()
        v = db.versions.current
        assert len(v.files[0]) == 0
        assert len(v.files[1]) > 0
        for i in range(100):
            assert db.get(b"k%04d" % i) == b"r4"


def test_range_tombstone_with_snapshot_not_resurrected(tmp_db_path):
    """Review regression: bottommost compaction must keep a range tombstone
    that is newer than a live snapshot, or deleted keys resurrect."""
    with DB.open(tmp_db_path, opts()) as db:
        db.put(b"k", b"v")
        snap = db.get_snapshot()
        db.delete_range(b"a", b"z")
        db.flush()
        db.compact_range()
        assert db.get(b"k") is None            # tombstone still effective
        assert db.get(b"k", ReadOptions(snapshot=snap)) == b"v"
        snap.release()
        db.compact_range()
        assert db.get(b"k") is None


def test_tombstones_with_many_outputs_no_overlap(tmp_db_path):
    """Review regression: surviving tombstones + output cutting must not
    produce overlapping files at L1+ (single-output mode)."""
    with DB.open(tmp_db_path, opts(target_file_size_base=4 * 1024)) as db:
        fill(db, 3000)
        snap = db.get_snapshot()  # keeps tombstone alive through compaction
        db.delete_range(b"key000100", b"key002900")
        db.flush()
        db.compact_range()       # would raise Corruption on overlap
        assert db.get(b"key000050") == b"v%08d" % 50
        assert db.get(b"key000500") is None
        snap.release()
    with DB.open(tmp_db_path, opts()) as db:  # recovery re-checks overlap
        assert db.get(b"key002950") == b"v%08d" % 2950


def test_background_error_surfaces_and_resume(tmp_db_path):
    with DB.open(tmp_db_path, opts()) as db:
        db.put(b"a", b"1")
        db._set_background_error(RuntimeError("boom"))
        with pytest.raises(Exception):
            db.put(b"b", b"2")
        with pytest.raises(Exception):
            db.wait_for_compactions()
        db.resume()
        db.put(b"b", b"2")
        assert db.get(b"b") == b"2"


def _db_dump(db):
    it = db.new_iterator()
    it.seek_to_first()
    return list(it.entries())


def test_subcompactions_same_content_as_single(tmp_db_path, tmp_path):
    """max_subcompactions>1 partitions the range across threads; merged
    content must equal the single-threaded result (reference subcompaction
    fan-out, compaction_job.cc:671-685)."""
    dumps = {}
    for sub in (1, 4):
        d = str(tmp_path / f"db_sub{sub}")
        o = Options(write_buffer_size=16 * 1024, max_subcompactions=sub,
                    disable_auto_compactions=True)
        with DB.open(d, o) as db:
            for i in range(3000):
                db.put(b"key%05d" % (i * 37 % 5000), b"v%05d" % i)
            db.flush()
            for i in range(0, 1500, 3):
                db.delete(b"key%05d" % (i * 37 % 5000))
            db.flush()
            db.compact_range()
            if sub > 1:
                # Boundaries must produce several output files at L1+.
                files = [f for lvl in db.versions.current.files[1:]
                         for f in lvl]
                assert len(files) > 1
            dumps[sub] = _db_dump(db)
    assert dumps[1] == dumps[4]


def test_subcompactions_tombstones_across_boundaries(tmp_path):
    """A range tombstone spanning several subcompaction ranges is clipped
    per range, never lost, never resurrecting (snapshot pins it live)."""
    dumps = {}
    for sub in (1, 4):
        d = str(tmp_path / f"db_rt{sub}")
        o = Options(write_buffer_size=16 * 1024, max_subcompactions=sub,
                    disable_auto_compactions=True)
        with DB.open(d, o) as db:
            for i in range(2000):
                db.put(b"key%05d" % i, b"v")
            db.flush()
            snap = db.get_snapshot()
            db.delete_range(b"key00200", b"key01800")  # spans boundaries
            db.flush()
            db.compact_range()
            assert db.get(b"key00199") == b"v"
            assert db.get(b"key00200") is None
            assert db.get(b"key01700") is None
            assert db.get(b"key01800") == b"v"
            assert db.get(b"key00500", ReadOptions(snapshot=snap)) == b"v"
            snap.release()
            dumps[sub] = _db_dump(db)
    assert dumps[1] == dumps[4]


def test_subcompactions_key_versions_not_split(tmp_path):
    """All versions of one user key stay in one subcompaction (boundaries
    are user keys), so snapshot-visible older versions survive."""
    for sub in (1, 4):
        d = str(tmp_path / f"db_ver{sub}")
        o = Options(write_buffer_size=8 * 1024, max_subcompactions=sub,
                    disable_auto_compactions=True)
        with DB.open(d, o) as db:
            for r in range(4):
                for i in range(500):
                    db.put(b"key%04d" % i, b"r%d" % r)
                db.flush()
            snap = db.get_snapshot()
            for i in range(500):
                db.put(b"key%04d" % i, b"new")
            db.flush()
            db.compact_range()
            assert db.get(b"key0250") == b"new"
            assert db.get(b"key0250", ReadOptions(snapshot=snap)) == b"r3"
            snap.release()


def test_trivial_move(tmp_db_path):
    """A lone file with nothing overlapping below relocates without rewrite
    (reference Compaction::IsTrivialMove) — same file number, new level."""
    o = Options(write_buffer_size=1 << 20, disable_auto_compactions=True,
                target_file_size_base=1 << 20)
    with DB.open(tmp_db_path, o) as db:
        for i in range(500):
            db.put(b"key%04d" % i, b"v" * 30)
        db.flush()
        f0 = db.versions.current.files[0][0].number
        db.compact_range()  # L0→L1 rewrites (L0 path), deeper levels move
        v = db.versions.current
        placed = [(lvl, f.number) for lvl in range(v.num_levels)
                  for f in v.files[lvl]]
        assert len(placed) == 1
        lvl, num = placed[0]
        assert lvl > 0
        # The deep levels were reached by MOVING the L1 output (same file
        # number persisted through multiple levels), not rewriting it.
        assert num != f0  # L0→L1 was a rewrite...
        assert db._compaction_scheduler.num_trivial_moves > 0, \
            "no trivial move recorded"
        assert db.get(b"key0250") == b"v" * 30
    with DB.open(tmp_db_path, o) as db:
        assert db.get(b"key0499") == b"v" * 30


def test_fifo_ttl_drops_old_files(tmp_db_path):
    """fifo_ttl_seconds: files older than the TTL are dropped even under
    the size budget (reference CompactionOptionsFIFO.ttl)."""
    from unittest import mock

    clock = [1_000_000.0]
    with mock.patch("time.time", lambda: clock[0]):
        o = Options(compaction_style="fifo", fifo_ttl_seconds=100,
                    fifo_max_table_files_size=1 << 30,
                    disable_auto_compactions=True)
        with DB.open(tmp_db_path, o) as db:
            for i in range(100):
                db.put(b"old%03d" % i, b"v")
            db.flush()
            clock[0] += 200  # first file expires
            for i in range(100):
                db.put(b"new%03d" % i, b"v")
            db.flush()
            db.options.disable_auto_compactions = False
            db._maybe_schedule_compaction()
            db.wait_for_compactions()
            assert db.get(b"old050") is None, "expired file kept"
            assert db.get(b"new050") == b"v"


def test_periodic_compaction_rewrites_old_files(tmp_db_path):
    """periodic_compaction_seconds: an aged file gets marked and rewritten
    (fresh creation_time), without data loss."""
    from unittest import mock

    clock = [2_000_000.0]
    with mock.patch("time.time", lambda: clock[0]):
        o = Options(periodic_compaction_seconds=500,
                    level0_file_num_compaction_trigger=100,
                    disable_auto_compactions=True)
        with DB.open(tmp_db_path, o) as db:
            for i in range(200):
                db.put(b"k%03d" % i, b"v%03d" % i)
            db.flush()
            before = {f.number for _, f in db.versions.current.all_files()}
            clock[0] += 1000  # age past the threshold
            db.options.disable_auto_compactions = False
            db._maybe_schedule_compaction()
            db.wait_for_compactions()
            after = {f.number for _, f in db.versions.current.all_files()}
            assert after and after != before, "aged file never rewritten"
            assert db.get(b"k100") == b"v100"
            # The rewrite refreshed creation_time: no immediate re-pick.
            db.wait_for_compactions()
            sched = db._compaction_scheduler
            n = sched.num_completed
            db._maybe_schedule_compaction()
            db.wait_for_compactions()
            assert sched.num_completed - n <= 1, "periodic rewrite loop"


def test_preclude_last_level_data_seconds(tmp_path):
    """The seqno<->time mapping's consumer (reference
    preclude_last_level_data_seconds): fresh data must NOT receive
    last-level treatment — seqnos stay un-zeroed (job retargets /
    drops bottommost semantics) until the data has aged past the
    cutoff."""
    import time as _time

    from toplingdb_tpu.db.db import DB
    from toplingdb_tpu.options import Options

    d = str(tmp_path / "db")
    with DB.open(d, Options(create_if_missing=True,
                            preclude_last_level_data_seconds=3600,
                            seqno_time_sample_period_sec=0)) as db:
        for i in range(2000):
            db.put(b"k%05d" % i, b"v" * 30)
        db.flush()
        # the mapping knows all data is recent
        db.seqno_to_time.append(db.versions.last_sequence,
                                int(_time.time()))
        db.compact_range(None, None)
        db.wait_for_compactions()
        v = db.versions.cf_current(0)
        # wherever the data landed, its seqnos must NOT be zeroed
        reader = db.table_cache.get_reader(
            next(f for _, f in v.all_files()).number)
        assert reader.properties.smallest_seqno > 0, \
            "fresh data received last-level seqno zeroing"
        assert db.get(b"k00042") == b"v" * 30

    # control: with the feature off the same flow zeroes seqnos
    d2 = str(tmp_path / "db2")
    with DB.open(d2, Options(create_if_missing=True)) as db:
        for i in range(2000):
            db.put(b"k%05d" % i, b"v" * 30)
        db.flush()
        db.compact_range(None, None)
        db.wait_for_compactions()
        v = db.versions.cf_current(0)
        reader = db.table_cache.get_reader(
            next(f for _, f in v.all_files()).number)
        assert reader.properties.smallest_seqno == 0


def test_seqno_time_mapping_survives_reopen(tmp_path):
    """The seqno<->time sidecar must persist: after a reopen, old data is
    still provably old, so preclude_last_level_data_seconds doesn't
    suppress last-level treatment for aged data."""
    import json as _json
    import os as _os
    import time as _time

    from toplingdb_tpu.db.db import DB
    from toplingdb_tpu.options import Options

    d = str(tmp_path / "db")
    with DB.open(d, Options(create_if_missing=True,
                            preclude_last_level_data_seconds=2)) as db:
        for i in range(500):
            db.put(b"k%04d" % i, b"v" * 20)
        db.flush()
    path = _os.path.join(d, "SEQNO_TIME.json")
    assert _os.path.exists(path)
    pairs = _json.loads(open(path).read())
    assert pairs and pairs[-1][1] > 0
    # age the recorded samples past the cutoff, reopen, compact: data is
    # provably old now -> last-level treatment applies (seqnos zero)
    aged = [[s_, t - 10] for s_, t in pairs]
    open(path, "w").write(_json.dumps(aged))
    with DB.open(d, Options(preclude_last_level_data_seconds=2)) as db:
        assert len(db.seqno_to_time) > 0
        db.compact_range(None, None)
        db.wait_for_compactions()
        v = db.versions.cf_current(0)
        reader = db.table_cache.get_reader(
            next(f for _, f in v.all_files()).number)
        assert reader.properties.smallest_seqno == 0


def test_intra_l0_compaction_when_base_busy(tmp_path):
    """Reference TryPickIntraL0Compaction: with the oldest L0 files busy
    (an L0->L1 job running), the picker merges the newest free contiguous
    run L0->L0 to keep read-amp falling — and the result preserves MVCC
    visibility + L0 seqno ordering."""
    from toplingdb_tpu.compaction.picker import LeveledCompactionPicker
    from toplingdb_tpu.db.db import DB
    from toplingdb_tpu.options import Options

    d = str(tmp_path / "db")
    with DB.open(d, Options(create_if_missing=True,
                            disable_auto_compactions=True)) as db:
        for gen in range(6):
            for i in range(200):
                db.put(b"k%04d" % i, b"gen%d" % gen)
            db.flush()
        v = db.versions.cf_current(0)
        assert len(v.files[0]) == 6
        # Simulate a running L0->L1 job holding the two OLDEST files.
        for f in v.files[0][4:]:
            f.being_compacted = True
        picker = LeveledCompactionPicker(db.options, db.icmp)
        c = picker.pick_compaction(v)
        assert c is not None and c.reason == "intra-L0"
        assert c.level == 0 and c.output_level == 0
        assert [f.number for f in c.inputs] == \
            [f.number for f in v.files[0][:4]]
        for f in v.files[0]:
            f.being_compacted = False
        # Run the intra-L0 merge through the real scheduler machinery.
        from toplingdb_tpu.compaction.compaction_job import (
            make_version_edit, run_compaction_to_tables,
        )

        counter = [db.versions._next_file_number + 50]

        def alloc():
            counter[0] += 1
            return counter[0]

        outputs, stats = run_compaction_to_tables(
            db.env, db.dbname, db.icmp, c, db.table_cache,
            db.options.table_options, [], new_file_number=alloc,
            creation_time=1)
        assert len(outputs) == 1
        edit = make_version_edit(c, outputs)
        with db._mutex:
            db.versions.log_and_apply(edit)
        v2 = db.versions.cf_current(0)
        assert len(v2.files[0]) == 3  # 4 merged into 1, plus 2 old
        # newest data (gen5) must still win for every key
        for i in range(0, 200, 7):
            assert db.get(b"k%04d" % i) == b"gen5"
