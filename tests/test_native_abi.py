"""The ctypes↔C ABI contract checker (tools/check_native_abi.py).

Static: the checker must pass over the real tree (every export bound,
every binding shaped by the C signature, every pointer paired in the
ARCHITECTURE.md §2.10.2 table), and must catch seeded contract breaks on
synthetic trees — a drifted argtype, an unbound export, a phantom
binding, a missing length pairing, a stale table row — each with a
file:line witness.
"""

import textwrap

from toplingdb_tpu.tools import check_native_abi as abi

# ---------------------------------------------------------------------------
# The real tree
# ---------------------------------------------------------------------------


def test_tree_is_clean_and_nonempty():
    assert abi.run() == []
    sigs, v = abi.parse_c_signatures(
        abi.os.path.join(abi.os.path.dirname(abi.__file__), "..",
                         "native", "tpulsm_native.cc"))
    assert v == []
    # The parser actually saw the surface (not a silently-empty scan).
    assert len(sigs) >= 65


def test_cli_exits_zero_on_clean_tree(capsys):
    assert abi.main([]) == 0
    out = capsys.readouterr().out
    assert "check_native_abi:" in out
    assert "0 violation(s)" in out


def test_every_export_has_a_contract_row():
    root = abi.os.path.dirname(abi.os.path.dirname(abi.os.path.dirname(
        abi.os.path.abspath(abi.__file__))))
    sigs, _ = abi.parse_c_signatures(abi.os.path.join(
        root, "toplingdb_tpu", "native", "tpulsm_native.cc"))
    rows, v = abi.parse_contract_table(abi.os.path.join(root,
                                                        "ARCHITECTURE.md"))
    assert v == []
    assert set(rows) == set(sigs)


# ---------------------------------------------------------------------------
# Seeded contract breaks on synthetic trees
# ---------------------------------------------------------------------------

_CC = """\
extern "C" {

int32_t tpulsm_add(const uint8_t* buf, int64_t n, int32_t flag) {
  return 0;
}

void* tpulsm_open(void) { return 0; }

}
"""

_INIT = """\
import ctypes


def lib():
    l = ctypes.CDLL("libx.so")
    u8p = ctypes.POINTER(ctypes.c_uint8)
    l.tpulsm_add.restype = ctypes.c_int32
    l.tpulsm_add.argtypes = [u8p, ctypes.c_int64, ctypes.c_int32]
    l.tpulsm_open.restype = ctypes.c_void_p
    l.tpulsm_open.argtypes = []
    return l
"""

_ARCH = """\
## Native

#### §2.10.2 ABI contract

| symbol | ret | argc | buffers |
|---|---|---|---|
| `tpulsm_add` | int32_t | 3 | `buf:n` |
| `tpulsm_open` | void* | 0 | — |
"""


def _tree(tmp_path, cc=_CC, init=_INIT, arch=_ARCH):
    nat = tmp_path / "toplingdb_tpu" / "native"
    nat.mkdir(parents=True)
    (nat / "tpulsm_native.cc").write_text(textwrap.dedent(cc))
    (nat / "__init__.py").write_text(textwrap.dedent(init))
    (tmp_path / "ARCHITECTURE.md").write_text(textwrap.dedent(arch))
    return abi.run(str(tmp_path))


def test_synthetic_baseline_is_clean(tmp_path):
    assert _tree(tmp_path) == []


def test_detects_drifted_argtype(tmp_path):
    out = _tree(tmp_path, init=_INIT.replace(
        "[u8p, ctypes.c_int64, ctypes.c_int32]",
        "[u8p, ctypes.c_int32, ctypes.c_int32]"))
    hits = [v for v in out if "argtypes[1]" in v and "tpulsm_add" in v]
    assert len(hits) == 1, out
    assert "__init__.py:" in hits[0]  # file:line witness


def test_detects_unbound_export(tmp_path):
    out = _tree(tmp_path, init="\n".join(
        ln for ln in _INIT.splitlines() if "tpulsm_open" not in ln) + "\n")
    hits = [v for v in out if "unbound export" in v]
    assert len(hits) == 1, out
    assert "tpulsm_open" in hits[0] and "tpulsm_native.cc" in hits[0]


def test_detects_phantom_binding(tmp_path):
    out = _tree(tmp_path, init=_INIT.replace(
        "    return l",
        "    l.tpulsm_ghost.restype = ctypes.c_int32\n"
        "    l.tpulsm_ghost.argtypes = []\n"
        "    return l"))
    hits = [v for v in out if "phantom binding" in v]
    assert len(hits) == 1, out
    assert "tpulsm_ghost" in hits[0] and "__init__.py:" in hits[0]


def test_detects_missing_length_pairing(tmp_path):
    out = _tree(tmp_path, arch=_ARCH.replace("`buf:n`", "—"))
    hits = [v for v in out if "no buffer-pairing spec" in v]
    assert len(hits) == 1, out
    assert "'buf'" in hits[0] and "tpulsm_add" in hits[0]


def test_detects_stale_table_row(tmp_path):
    out = _tree(tmp_path, arch=_ARCH.replace(
        "| `tpulsm_add` | int32_t | 3 |", "| `tpulsm_add` | int32_t | 2 |"))
    hits = [v for v in out if "stale row" in v and "tpulsm_add" in v]
    assert len(hits) == 1, out


def test_detects_missing_table_row(tmp_path):
    out = _tree(tmp_path, arch=_ARCH.replace(
        "| `tpulsm_open` | void* | 0 | — |\n", ""))
    hits = [v for v in out if "missing a row" in v]
    assert len(hits) == 1, out
    assert "tpulsm_open" in hits[0]


def test_detects_restype_drift(tmp_path):
    out = _tree(tmp_path, init=_INIT.replace(
        "l.tpulsm_add.restype = ctypes.c_int32",
        "l.tpulsm_add.restype = ctypes.c_int64"))
    hits = [v for v in out if "restype" in v and "tpulsm_add" in v]
    assert len(hits) == 1, out
    assert "__init__.py:" in hits[0]


def test_mutable_buffer_refuses_c_char_p(tmp_path):
    """c_char_p points at immutable Python bytes — binding a non-const
    C out-buffer to it is the classic silent-corruption drift."""
    cc = _CC.replace("const uint8_t* buf", "uint8_t* buf")
    init = _INIT.replace("[u8p,", "[ctypes.c_char_p,")
    out = _tree(tmp_path, cc=cc, init=init)
    hits = [v for v in out if "argtypes[0]" in v]
    assert len(hits) == 1, out
    assert "c_char_p" in hits[0]
