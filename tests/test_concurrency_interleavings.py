"""Sync-point interleaving tests for the two cross-thread seams the
concurrency plane pins down:

1. db.py — a staged write group's async-WAL durability barrier vs the
   memtable switch (which closes the WAL the group appended to). The
   `_mt_inflight` drain in `_switch_memtable` is the protocol; the
   dependency forces the switch to start only once a group has entered
   its barrier window, so the drain handshake (cv wait vs completion
   notify) actually runs under contention.
2. sharding — a writer parked at a closed write fence vs the migration
   cutover. The dependency holds the cutover until a writer is parked,
   so the parked writer MUST wake into the post-swap world and
   re-resolve onto the new primary (epoch bump).

Both tests drive the orders with
`get_sync_point_registry().load_dependency(...)` — no sleeps.
"""

import threading
import time

import pytest

from toplingdb_tpu.db.db import DB
from toplingdb_tpu.options import FlushOptions, Options, WriteOptions
from toplingdb_tpu.sharding import ShardMigration, open_local_cluster
from toplingdb_tpu.utils.statistics import Statistics
from toplingdb_tpu.utils.sync_point import get_sync_point_registry


@pytest.fixture
def sync_points():
    reg = get_sync_point_registry()
    reg.clear_all()
    yield reg
    reg.clear_all()


def test_wal_barrier_vs_memtable_switch(tmp_path, sync_points):
    """Forced order: a pipelined group reaches its async-WAL barrier ->
    THEN the flush thread's memtable switch may start. The switch closes
    the group's WAL; every acknowledged write must survive reopen."""
    reg = sync_points
    opts = Options(create_if_missing=True, enable_pipelined_write=True,
                   enable_async_wal=True)
    db = DB.open(str(tmp_path / "db"), opts)
    at_barrier = threading.Event()
    reg.set_callback("DBImpl::GroupCommit:BeforeWALBarrier",
                     lambda _arg: at_barrier.set())
    reg.load_dependency([
        ("DBImpl::GroupCommit:BeforeWALBarrier",
         "DBImpl::SwitchMemtable:Start"),
    ])
    reg.enable_processing()

    err = []

    def writer():
        try:
            for i in range(50):
                db.put(b"k%04d" % i, b"v%d" % i,
                       WriteOptions(sync=(i % 7 == 0)))
        except BaseException as e:  # noqa: BLE001
            err.append(e)

    t = threading.Thread(target=writer, name="interleave-writer")
    t.start()
    # The flush (and its switch) may only start once a write group is in
    # its barrier window; the event keeps the mutex free until then so
    # the dependency cannot deadlock the leader out of ever reaching it.
    assert at_barrier.wait(timeout=30.0), "no group reached the barrier"
    db.flush(FlushOptions())
    t.join(timeout=30.0)
    assert not t.is_alive()
    assert not err, err
    reg.clear_all()
    db.close()

    db2 = DB.open(str(tmp_path / "db"), Options())
    try:
        for i in range(50):
            assert db2.get(b"k%04d" % i) == b"v%d" % i
    finally:
        db2.close()


def test_fenced_writer_vs_migration_cutover(tmp_path, sync_points):
    """Forced order: the migration cutover waits until a writer is
    parked at the closed fence. The parked writer must wake AFTER the
    swap + epoch bump and land its write on the NEW primary."""
    reg = sync_points
    r = open_local_cluster(str(tmp_path),
                           [("a", None, b"m"), ("b", b"m", None)],
                           statistics=Statistics())
    old_primary = None
    try:
        for i in range(120):
            r.put(b"m%05d" % i, b"v%d" % i)
        old_primary = r._serving("b").primary
        old_epoch = r.map.get("b").epoch

        reg.load_dependency([
            ("ShardRouter::WriteGate:Parked",
             "ShardMigration::BeforeCutover"),
        ])
        reg.enable_processing()

        mig_out, mig_err = [], []

        def migrate():
            try:
                mig_out.append(
                    ShardMigration(r, "b", str(tmp_path / "b-new")).run())
            except BaseException as e:  # noqa: BLE001
                mig_err.append(e)

        mt = threading.Thread(target=migrate, name="interleave-migrate")
        mt.start()
        # Wait for the fence to close, then write: the writer parks at
        # the gate, which is what releases the cutover.
        for _ in range(3000):
            if r.map.get("b").state == "fenced":
                break
            time.sleep(0.01)
        assert r.map.get("b").state == "fenced"
        tok = r.put(b"m88888", b"post-cutover")
        mt.join(timeout=60.0)
        assert not mt.is_alive()
        assert not mig_err, mig_err
        reg.clear_all()

        # The parked write re-resolved onto the NEW primary/epoch.
        assert tok.epoch == r.map.get("b").epoch
        assert tok.epoch > old_epoch
        assert r._serving("b").primary is not old_primary
        assert r.get(b"m88888", token=tok) == b"post-cutover"
        # Cutover retires the replaced stack (the old primary is closed,
        # so no late write can ever land there); reopen its directory to
        # prove the parked write was never applied to it.
        assert old_primary._closed
        reopened = DB.open(old_primary.dbname,
                           Options(create_if_missing=False))
        try:
            assert reopened.get(b"m88888") is None
        finally:
            reopened.close()
        assert r.get(b"m00042") == b"v42"
    finally:
        reg.clear_all()
        r.close()
