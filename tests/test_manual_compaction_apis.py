"""CompactFiles / SuggestCompactRange / PromoteL0 (reference db.h manual
compaction APIs), RemapEnv (env/fs_remap.cc role), and the benchmark
regression tooling (tools/benchmark.sh + benchmark_compare.sh role)."""

import json

import pytest

from toplingdb_tpu.db.db import DB
from toplingdb_tpu.options import Options
from toplingdb_tpu.utils.status import Busy, InvalidArgument


def _db_with_l0_files(tmp_path, n_files=3, overlap=True):
    db = DB.open(str(tmp_path / "db"), Options(
        level0_file_num_compaction_trigger=100,  # no auto compaction
    ))
    for i in range(n_files):
        lo = 0 if overlap else i * 100
        for j in range(lo, lo + 100):
            db.put(b"key%06d" % j, b"f%d-%d" % (i, j))
        db.flush()
    return db


def test_compact_files(tmp_path):
    db = _db_with_l0_files(tmp_path)
    version = db.versions.cf_current(0)
    nums = [f.number for f in version.files[0]]
    assert len(nums) == 3
    db.compact_files(nums, output_level=2)
    version = db.versions.cf_current(0)
    assert not version.files[0]
    assert version.files[2]
    for j in range(100):
        assert db.get(b"key%06d" % j) == b"f2-%d" % j  # newest file wins
    with pytest.raises(InvalidArgument):
        db.compact_files([999999], output_level=2)  # not live
    db.close()


def test_compact_files_level_validation(tmp_path):
    """Reference SanitizeCompactionInputFilesForAllLevels
    (compaction_picker.cc:908) EXPANDS a partial input set: at L0 every
    file older than the newest listed file comes along; overlapping
    output-level files are pulled in automatically."""
    db = _db_with_l0_files(tmp_path)
    version = db.versions.cf_current(0)
    nums = [f.number for f in version.files[0]]  # newest-first
    # The OLDEST L0 file alone: nothing older to pull in — moves by itself,
    # newer overlapping runs legally stay above it.
    db.compact_files(nums[-1:], output_level=1)
    version = db.versions.cf_current(0)
    assert len(version.files[0]) == 2 and len(version.files[1]) == 1
    # The NEWEST remaining L0 file: the older overlapping L0 file AND the
    # overlapping L1 file are auto-included (else reads would find stale
    # data above the moved output).
    db.compact_files([version.files[0][0].number], output_level=1)
    version = db.versions.cf_current(0)
    assert not version.files[0] and version.files[1]
    for j in range(100):
        assert db.get(b"key%06d" % j) == b"f2-%d" % j  # newest still wins
    # compacting upward is rejected
    with pytest.raises(InvalidArgument):
        db.compact_files([version.files[1][0].number], output_level=0)
    db.close()


def test_suggest_compact_range(tmp_path):
    db = _db_with_l0_files(tmp_path, overlap=False)
    marked = db.suggest_compact_range(b"key000150", b"key000250")
    version = db.versions.cf_current(0)
    flagged = [f for _, f in version.all_files() if f.marked_for_compaction]
    assert marked == len(flagged) and 1 <= marked <= 2
    # idempotent
    assert db.suggest_compact_range(b"key000150", b"key000250") == 0
    db.close()


def test_promote_l0(tmp_path):
    db = _db_with_l0_files(tmp_path, overlap=False)  # disjoint L0 files
    db.promote_l0(target_level=2)
    version = db.versions.cf_current(0)
    assert not version.files[0] and len(version.files[2]) == 3
    for j in range(250, 260):
        assert db.get(b"key%06d" % j) == b"f2-%d" % j
    db.close()
    # survives reopen (metadata-only move went through the MANIFEST)
    db = DB.open(str(tmp_path / "db"), Options())
    assert db.get(b"key000000") == b"f0-0"
    db.close()


def test_promote_l0_rejects_overlap(tmp_path):
    db = _db_with_l0_files(tmp_path, overlap=True)
    with pytest.raises(InvalidArgument):
        db.promote_l0()
    db.close()


def test_remap_env(tmp_path):
    from toplingdb_tpu.env import default_env
    from toplingdb_tpu.env.remap import RemapEnv

    real = str(tmp_path / "real")
    env = RemapEnv(default_env(), {"/virtual/db": real,
                                   "/virtual/db/sub": str(tmp_path / "sub")})
    env.create_dir("/virtual/db")
    env.write_file("/virtual/db/x.txt", b"hello", sync=True)
    assert (tmp_path / "real" / "x.txt").read_bytes() == b"hello"
    assert env.read_file("/virtual/db/x.txt") == b"hello"
    assert env.file_exists("/virtual/db/x.txt")
    assert env.get_file_size("/virtual/db/x.txt") == 5
    # longest prefix wins
    env.create_dir("/virtual/db/sub")
    env.write_file("/virtual/db/sub/y.txt", b"yy")
    assert (tmp_path / "sub" / "y.txt").read_bytes() == b"yy"
    # unmapped paths pass through
    p = str(tmp_path / "plain.txt")
    env.write_file(p, b"p")
    assert env.read_file(p) == b"p"
    env.rename_file("/virtual/db/x.txt", "/virtual/db/z.txt")
    assert env.get_children("/virtual/db") == ["z.txt"]
    # a whole DB works through the remap
    db = DB.open("/virtual/db2", Options(),
                 env=RemapEnv(default_env(), {"/virtual/db2":
                                              str(tmp_path / "db2")}))
    db.put(b"k", b"v")
    db.flush()
    db.close()
    assert (tmp_path / "db2").is_dir()
    db = DB.open("/virtual/db2", Options(),
                 env=RemapEnv(default_env(), {"/virtual/db2":
                                              str(tmp_path / "db2")}))
    assert db.get(b"k") == b"v"
    db.close()


def test_benchmark_suite_and_compare(tmp_path, capsys):
    from toplingdb_tpu.tools.benchmark import main as bench_main
    from toplingdb_tpu.tools.benchmark_compare import main as cmp_main

    out1 = str(tmp_path / "base.json")
    out2 = str(tmp_path / "new.json")
    for out in (out1, out2):
        rc = bench_main([
            "--suite", "quick", "--num", "2000",
            "--db", str(tmp_path / "benchdb"), "--out", out,
        ])
        assert rc == 0
        doc = json.loads(open(out).read())
        assert {r["name"] for r in doc["results"]} == {"fillseq", "readrandom"}
        assert all(r["ops_per_sec"] > 0 for r in doc["results"])
    assert cmp_main([out1, out2, "--threshold", "0.01"]) == 0
    # forge a regression
    doc = json.loads(open(out2).read())
    doc["results"][0]["ops_per_sec"] = 1.0
    open(out2, "w").write(json.dumps(doc))
    assert cmp_main([out1, out2, "--threshold", "0.85"]) == 1
    capsys.readouterr()
