"""Corrupt-input robustness for the NATIVE parsers (the fuzz/ role of the
reference, sst_file_writer_fuzzer.cc + db_fuzzer.cc shapes): random and
bit-flipped inputs must produce clean errors/fallbacks, never crashes or
silent acceptance of torn frames."""

import random

import pytest


def _lib():
    from toplingdb_tpu import native

    lib = native.lib()
    if lib is None:
        pytest.skip("native library unavailable")
    return lib


def test_writebatch_wire_parser_rejects_garbage():
    """tpulsm_*_insert_wb: random byte soup and mutated valid images must
    be rejected (rc<0) or applied cleanly — never crash, and pass-0
    validation means a rejected batch inserts NOTHING."""
    from toplingdb_tpu.db.memtable import NativeSkipListRep, NativeTrieRep
    from toplingdb_tpu.db.write_batch import WriteBatch

    rng = random.Random(7)
    for rep_cls in (NativeSkipListRep, NativeTrieRep):
        try:
            rep = rep_cls()
        except RuntimeError:
            pytest.skip("native library unavailable")
        # pure garbage
        for _ in range(300):
            blob = bytes(rng.randrange(256)
                         for _ in range(rng.randrange(0, 120)))
            r = rep.insert_wb(blob, 1)
            assert r is None or r[0] >= 0
            assert len(rep) == 0, "rejected batch must insert nothing"
        # mutated valid image
        wb = WriteBatch()
        for i in range(20):
            wb.put(b"k%03d" % i, b"v%d" % i)
        good = wb.data()
        applied = 0
        for _ in range(400):
            blob = bytearray(good)
            for _ in range(rng.randrange(1, 4)):
                blob[rng.randrange(len(blob))] ^= 1 << rng.randrange(8)
            before = len(rep)
            r = rep.insert_wb(bytes(blob), 1000 + applied * 50)
            if r is None:
                assert len(rep) == before, "rejected batch inserted rows"
            else:
                applied += 1
        # the rep must still be coherent: iteration strictly ordered
        last = None
        for (uk, inv), v in rep.iter_all():
            if last is not None:
                assert (uk, inv) > last, (last, uk, inv)
            last = (uk, inv)


def test_block_decoder_rejects_corrupt_blocks():
    """tpulsm_block_seek / the bulk decoders: random payloads with a valid
    length field must never crash; decode either errors or returns
    bounded results."""
    import ctypes

    import numpy as np

    from toplingdb_tpu import native

    lib = _lib()
    rng = random.Random(9)
    key_out = (ctypes.c_uint8 * 4096)()
    out = (ctypes.c_int32 * 6)()
    for _ in range(500):
        n = rng.randrange(8, 300)
        blob = bytes(rng.randrange(256) for _ in range(n))
        rc = lib.tpulsm_block_seek(blob, n, b"probe\x00\x00\x00\x01\x01"
                                   b"\x00\x00\x00\x00\x00\x00", 13,
                                   key_out, 4096, out)
        assert rc in (-2, -1, 0, 1)


def test_reader_surfaces_corruption_not_crash(tmp_path):
    """Flip bytes across a real SST; every read path (open, point get,
    scan, columnar bulk scan) must either succeed or raise Corruption —
    never crash or return torn values silently when checksums are on."""
    from toplingdb_tpu.db.dbformat import InternalKeyComparator, ValueType, make_internal_key
    from toplingdb_tpu.env import default_env
    from toplingdb_tpu.table.builder import TableOptions
    from toplingdb_tpu.table.factory import new_table_builder, open_table
    from toplingdb_tpu.utils.status import Corruption, NotSupported

    env = default_env()
    icmp = InternalKeyComparator()
    path = str(tmp_path / "f.sst")
    w = env.new_writable_file(path)
    b = new_table_builder(w, icmp, TableOptions(block_size=512))
    for i in range(2000):
        b.add(make_internal_key(b"k%05d" % i, i + 1, ValueType.VALUE),
              b"value%05d" % i)
    b.finish()
    w.close()
    good = open(path, "rb").read()
    rng = random.Random(3)
    crashes = 0
    for trial in range(120):
        blob = bytearray(good)
        for _ in range(rng.randrange(1, 6)):
            blob[rng.randrange(len(blob))] ^= 1 << rng.randrange(8)
        open(path, "wb").write(bytes(blob))
        try:
            r = open_table(env.new_random_access_file(path), icmp,
                           TableOptions(verify_checksums=True))
            it = r.new_iterator()
            it.seek(make_internal_key(b"k00500", 2**56 - 1, 0x7F))
            while it.valid():
                it.key(), it.value()
                it.next()
            from toplingdb_tpu.ops.columnar_io import scan_table_columnar

            scan_table_columnar(r)
        except (Corruption, NotSupported):
            pass  # the classified error corruption should surface as
        # (Anything else — IndexError, struct.error — is a parser bug
        # the flip exposed and fails the test; a segfault would kill the
        # whole run.)
    open(path, "wb").write(good)
