"""Async read plane (env/async_reads.py + TPULSM_ASYNC_READS=1):
ring task back-pressure, batch coalescing, closed-batcher fallback,
sync/async byte parity across table formats x codecs x snapshots x
range tombstones, fault injection through the reader rings, and
thread hygiene (DB.close joins every reader-ring thread)."""

import os
import threading
import time

import pytest

from toplingdb_tpu.db.db import DB
from toplingdb_tpu.env.async_reads import AsyncReadBatcher
from toplingdb_tpu.env.env import AsyncIORing
from toplingdb_tpu.env.fault_injection import ReadFaultInjector
from toplingdb_tpu.options import Options, ReadOptions
from toplingdb_tpu.table.builder import TableOptions
from toplingdb_tpu.table import format as fmt
from toplingdb_tpu.utils import statistics as st
from toplingdb_tpu.utils.statistics import Statistics
from toplingdb_tpu.utils.status import IOError_


@pytest.fixture
def async_knob():
    """Restore TPULSM_ASYNC_READS after each test."""
    saved = os.environ.get("TPULSM_ASYNC_READS")
    yield
    if saved is None:
        os.environ.pop("TPULSM_ASYNC_READS", None)
    else:
        os.environ["TPULSM_ASYNC_READS"] = saved


def set_knob(v: str) -> None:
    os.environ["TPULSM_ASYNC_READS"] = v


class _StubFile:
    """read(offset, n)/append(data) double that counts carrier preads."""

    def __init__(self, data: bytes = b""):
        self.data = bytearray(data)
        self.reads = 0
        self.read_ranges = []

    def read(self, offset: int, n: int) -> bytes:
        self.reads += 1
        self.read_ranges.append((offset, n))
        return bytes(self.data[offset:offset + n])

    def append(self, data: bytes) -> None:
        self.data += data

    def flush(self) -> None:
        pass


# ---------------------------------------------------------------------------
# Satellite (a): AsyncIORing task back-pressure
# ---------------------------------------------------------------------------


def test_ring_task_submissions_are_bounded(no_thread_leaks):
    """submit_task must hit back-pressure at task_capacity: before the
    fix, the capacity wait was gated on kind == "append", so a fast
    producer could grow the queue without bound."""
    ring = AsyncIORing(capacity=64, task_capacity=4, name="bp-test")
    gate = threading.Event()
    running = threading.Event()
    try:
        # Wedge the worker mid-round so later submissions pile up.
        blocker = ring.submit_task(
            lambda: (running.set(), gate.wait(timeout=10.0)))
        assert running.wait(timeout=5.0)
        toks = [ring.submit_task(lambda i=i: i) for i in range(4)]

        stalled = threading.Event()
        passed = threading.Event()

        def overflow():
            stalled.set()
            ring.submit_task(lambda: 99)  # 5th queued task: must block
            passed.set()

        t = threading.Thread(target=overflow, daemon=True)
        t.start()
        assert stalled.wait(timeout=5.0)
        time.sleep(0.1)
        assert not passed.is_set(), "task submission was NOT back-pressured"

        # Appends have their own (larger) budget: a full task queue must
        # not block the WAL lane.
        f = _StubFile()
        t0 = time.monotonic()
        ring.submit_append(f, b"wal-bytes")
        assert time.monotonic() - t0 < 1.0

        gate.set()  # drain: the blocked producer gets through
        assert passed.is_set() or passed.wait(timeout=5.0)
        for tok in toks:
            tok.wait()
        blocker.wait()
        t.join(timeout=5.0)
    finally:
        gate.set()
        ring.close()
    assert bytes(f.data) == b"wal-bytes"


# ---------------------------------------------------------------------------
# Batcher unit tests: coalescing, max_span, closed fallback
# ---------------------------------------------------------------------------


def test_batcher_coalesces_adjacent_ranges(no_thread_leaks):
    stats = Statistics()
    payload = bytes(range(256)) * 64  # 16 KiB
    f = _StubFile(payload)
    b = AsyncReadBatcher(rings=2, stats=stats, name="coal-test")
    try:
        reqs = [(f, 0, 100), (f, 100, 100), (f, 150, 200),  # one run
                (f, 8000, 64)]                               # detached
        toks = b.submit_batch(reqs)
        got = [t.wait() for t in toks]
        assert got == [payload[0:100], payload[100:200],
                       payload[150:350], payload[8000:8064]]
        assert f.reads == 2  # 3 adjacent requests -> 1 carrier pread
        assert sorted(f.read_ranges) == [(0, 350), (8000, 64)]
        assert b.batches == 1 and b.coalesced == 2 and b.fallbacks == 0
        assert stats.get_ticker_count(st.READ_ASYNC_BATCHES) == 1
        assert stats.get_ticker_count(st.READ_ASYNC_COALESCED) == 2
    finally:
        b.close()


def test_batcher_max_span_bounds_carrier_reads(no_thread_leaks):
    f = _StubFile(b"x" * 4096)
    b = AsyncReadBatcher(rings=1, name="span-test")
    b.max_span = 256
    try:
        toks = b.submit_batch([(f, i * 128, 128) for i in range(8)])
        assert all(t.wait() == b"x" * 128 for t in toks)
        # 8 adjacent 128-byte requests, 256-byte cap -> 4 carrier preads
        assert f.reads == 4
        assert all(n <= 256 for _, n in f.read_ranges)
    finally:
        b.close()


def test_closed_batcher_serves_inline(no_thread_leaks):
    stats = Statistics()
    f = _StubFile(b"abcdefgh" * 16)
    b = AsyncReadBatcher(rings=2, stats=stats, name="closed-test")
    b.close()
    toks = b.submit_batch([(f, 0, 8), (f, 64, 8)])
    assert [t.wait() for t in toks] == [b"abcdefgh", b"abcdefgh"]
    assert b.fallbacks > 0
    assert stats.get_ticker_count(st.READ_ASYNC_FALLBACKS) > 0
    tok = b.submit_task(lambda: 41 + 1)
    assert tok.wait() == 42


# ---------------------------------------------------------------------------
# Sync/async parity matrix (tentpole): block + zip x codecs x snapshots
# x range tombstones, byte-identical across TPULSM_ASYNC_READS=0/1
# ---------------------------------------------------------------------------


def _build_matrix_db(path, table_options):
    """Several SSTs + overwrites + a snapshot pinning pre-tombstone
    state + a range tombstone + live memtable entries."""
    db = DB.open(path, Options(
        create_if_missing=True, write_buffer_size=16 * 1024,
        statistics=Statistics(), table_options=table_options))
    n = 500
    for i in range(n):
        db.put(b"key%05d" % i, b"val-%05d-" % i + b"p" * (i % 37))
    db.flush()
    for i in range(0, n, 3):
        db.put(b"key%05d" % i, b"OVR-%05d" % i)
    db.flush()
    db.wait_for_compactions()
    snap = db.get_snapshot()
    db.delete_range(b"key00100", b"key00160")
    for i in range(200, 230):
        db.put(b"key%05d" % i, b"mem-%05d" % i)  # stays in memtable
    return db, snap, n


PARITY_CASES = [
    ("block-none", TableOptions(block_size=512,
                                compression=fmt.NO_COMPRESSION)),
    ("block-zstd", TableOptions(block_size=512,
                                compression=fmt.ZSTD_COMPRESSION)),
    ("zip-none", TableOptions(format="zip",
                              compression=fmt.NO_COMPRESSION)),
    ("zip-zstd", TableOptions(format="zip",
                              compression=fmt.ZSTD_COMPRESSION)),
]


@pytest.mark.parametrize("label,topts", PARITY_CASES,
                         ids=[c[0] for c in PARITY_CASES])
def test_sync_async_parity_matrix(tmp_db_path, async_knob, no_thread_leaks,
                                  label, topts):
    db, snap, n = _build_matrix_db(tmp_db_path, topts)
    try:
        keys = [b"key%05d" % i for i in range(n)] + [b"nope", b"zzzz"]

        def observe():
            out = {
                "mget": db.multi_get(keys),
                "mget_snap": db.multi_get(
                    keys[::7], ReadOptions(snapshot=snap)),
                "gets": [db.get(k) for k in keys[::13]],
                "get_snap": db.get(b"key00120", ReadOptions(snapshot=snap)),
            }
            it = db.new_iterator()
            it.seek_to_first()
            out["scan"] = list(it.entries())
            fut = db.multi_get_async(keys[::11])
            out["mget_async"] = fut.result()
            return out

        set_knob("0")
        sync_view = observe()
        set_knob("1")
        async_view = observe()
        assert async_view == sync_view  # byte-identical, all surfaces
        # the tombstoned range really exercises deletes at both knobs
        assert sync_view["mget"][110] is None
        assert sync_view["get_snap"] is not None
        # knob=1 actually drove the batcher (cold blocks / compressed
        # value groups were planned). zip-none has nothing to prefetch:
        # the table is fully resident and its value groups uncompressed.
        if label != "zip-none":
            assert db.stats.get_ticker_count(st.READ_ASYNC_BATCHES) > 0
    finally:
        db.release_snapshot(snap)
        db.close()


# ---------------------------------------------------------------------------
# Fault injection through the reader rings
# ---------------------------------------------------------------------------


def test_async_read_error_propagates_then_resumes(tmp_db_path, async_knob,
                                                  no_thread_leaks):
    db, snap, n = _build_matrix_db(
        tmp_db_path, TableOptions(block_size=512,
                                  compression=fmt.NO_COMPRESSION))
    try:
        db.release_snapshot(snap)
        keys = [b"key%05d" % i for i in range(n)]
        set_knob("0")
        oracle = db.multi_get(keys)
        # Injector armed BEFORE the first async read: the batcher wires
        # db.read_fault_hook into its rings at creation.
        db.read_fault_hook = ReadFaultInjector(schedule={0: "fail"})
        set_knob("1")
        with pytest.raises(IOError_, match="injected reader-ring"):
            db.multi_get(keys)
        # Schedule exhausted -> the SAME rings serve cleanly (the error
        # settled one token, it did not poison the ring).
        assert db.multi_get(keys) == oracle
        assert db.read_fault_hook.injected_counts() == {"fail": 1}
    finally:
        db.close()


def test_async_read_delay_plan_keeps_parity(tmp_db_path, async_knob,
                                            no_thread_leaks):
    db, snap, n = _build_matrix_db(
        tmp_db_path, TableOptions(block_size=512,
                                  compression=fmt.NO_COMPRESSION))
    try:
        db.release_snapshot(snap)
        keys = [b"key%05d" % i for i in range(n)]
        set_knob("0")
        oracle = db.multi_get(keys)
        db.read_fault_hook = ReadFaultInjector(rate=1.0, plans=("delay",),
                                               delay_sec=0.0002)
        set_knob("1")
        assert db.multi_get(keys) == oracle
        assert db.read_fault_hook.injected_counts().get("delay", 0) > 0
    finally:
        db.close()


# ---------------------------------------------------------------------------
# Thread hygiene + async API
# ---------------------------------------------------------------------------


def test_db_close_joins_reader_rings(tmp_db_path, async_knob,
                                     no_thread_leaks):
    """Zero leaked ring threads after DB.close (acceptance criterion).
    The no_thread_leaks fixture asserts the ccy registry is clean."""
    from toplingdb_tpu.utils import concurrency as ccy

    db, snap, _ = _build_matrix_db(
        tmp_db_path, TableOptions(block_size=512,
                                  compression=fmt.NO_COMPRESSION))
    db.release_snapshot(snap)
    set_knob("1")
    db.multi_get([b"key%05d" % i for i in range(0, 500, 5)])
    it = db.new_iterator()
    it.seek_to_first()
    next(iter(it.entries()), None)
    fut = db.get_async(b"key00042")
    assert fut.result() == db.get(b"key00042")
    before = {t.name for t in ccy.registry.live()}
    assert any(n.startswith("aio-tpulsm-read") for n in before)
    db.close()
    after = {t.name for t in ccy.registry.live()}
    assert not any(n.startswith("aio-tpulsm-read") for n in after)


def test_get_async_multi_get_async_futures(tmp_db_path, async_knob,
                                           no_thread_leaks):
    db = DB.open(tmp_db_path, Options(create_if_missing=True,
                                      statistics=Statistics()))
    try:
        for i in range(64):
            db.put(b"k%03d" % i, b"v%03d" % i)
        db.flush()
        set_knob("1")
        futs = [db.get_async(b"k%03d" % i) for i in range(0, 64, 4)]
        assert [f.result() for f in futs] == \
            [b"v%03d" % i for i in range(0, 64, 4)]
        mf = db.multi_get_async([b"k001", b"missing", b"k050"])
        assert mf.result() == [b"v001", None, b"v050"]
    finally:
        db.close()


def test_shard_router_fans_out_concurrently(tmp_path, async_knob,
                                            no_thread_leaks):
    """Front-door parity: a multi-shard batch reassembles byte-identical
    results through the future-based fan-out, tokened or not."""
    from toplingdb_tpu.sharding import open_local_cluster

    for knob in ("0", "1"):
        set_knob(knob)
        base = tmp_path / ("cluster" + knob)
        r = open_local_cluster(str(base),
                               [("a", None, b"m"), ("b", b"m", None)],
                               statistics=Statistics())
        try:
            rows = {b"a%04d" % i: b"v%d" % i for i in range(80)}
            rows.update({b"z%04d" % i: b"w%d" % i for i in range(80)})
            toks = {k: r.put(k, v) for k, v in rows.items()}
            keys = list(rows) + [b"absent", b"zz-absent"]
            want = [rows.get(k) for k in keys]
            assert r.multi_get(keys) == want
            tok = toks[b"a0001"]
            tok = tok[0] if isinstance(tok, list) else tok
            assert r.multi_get(keys, token=tok) == want
        finally:
            r.close()
