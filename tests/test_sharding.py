"""Sharding plane: shard map, front-door router, split/merge/migration,
per-tenant admission control.

Acceptance matrix:
  - ShardMap invariants: tiling, epoch monotonicity, JSON round-trip
  - routing + read-your-writes ShardTokens across shards
  - split/merge/migration invalidate tokens (rejected + re-routed, never
    served from a pre-change epoch — proven with a poisoned stale replica)
  - ReplicaRouter-level epoch tokens (the PR's staleness-token fix)
  - DB.write_stall_state() + stall tickers + /metrics gauges
  - admission control: bounded-wait rate limits, stall shedding, sibling
    isolation
  - migration chaos soak: 30% ship faults + a kill mid-migration converge
    to parity with a merged oracle; zero lost or double-served keys
  - HTTP control plane (/shards views, POST split/migrate) + shard_admin
"""

import json
import random
import threading
import urllib.request

import pytest

from toplingdb_tpu.db.db import DB
from toplingdb_tpu.db.write_batch import WriteBatch
from toplingdb_tpu.env.fault_injection import ShipFaultInjector
from toplingdb_tpu.options import Options
from toplingdb_tpu.replication import (
    FaultyTransport,
    ReplicaRouter,
    StalenessToken,
)
from toplingdb_tpu.sharding import (
    AdmissionController,
    BalancerOptions,
    MigrationAborted,
    ShardBalancer,
    ShardMap,
    ShardMigration,
    ShardRouter,
    TenantQuota,
    open_local_cluster,
)
from toplingdb_tpu.utils import statistics as st
from toplingdb_tpu.utils.statistics import Statistics
from toplingdb_tpu.utils.status import Busy, InvalidArgument


def opts(**kw):
    kw.setdefault("create_if_missing", True)
    kw.setdefault("write_buffer_size", 1 << 20)
    kw.setdefault("statistics", Statistics())
    return Options(**kw)


def cluster(tmp_path, stats=None, bounds=(("a", None, b"m"),
                                          ("b", b"m", None)), **kw):
    return open_local_cluster(str(tmp_path), list(bounds),
                              statistics=stats or Statistics(), **kw)


# -- shard map ---------------------------------------------------------------


def test_shard_map_invariants_and_json_roundtrip():
    m = ShardMap.from_bounds([("a", None, b"g"), ("b", b"g", b"t"),
                              ("c", b"t", None)])
    assert m.shard_for(b"apple").name == "a"
    assert m.shard_for(b"g").name == "b"      # start inclusive
    assert m.shard_for(b"szz").name == "b"    # end exclusive
    assert m.shard_for(b"t").name == "c"
    v0 = m.version
    left, right = m.split("b", b"m")
    assert (left.name, right.name) == ("b", "s3")
    assert m.version > v0
    # fresh epochs on BOTH halves, never reused
    assert left.epoch != right.epoch
    assert left.epoch > 3 and right.epoch > 3
    merged = m.merge("b", "s3")
    assert merged.epoch > max(left.epoch, right.epoch)
    assert m.names() == ["a", "b", "c"]

    m2 = ShardMap.from_config(m.to_config())
    assert m2.to_config() == m.to_config()
    # epoch monotonicity survives reload
    assert m2.bump_epoch("a") > merged.epoch

    with pytest.raises(InvalidArgument):
        m.split("a", b"zz")  # outside range
    with pytest.raises(InvalidArgument):
        m.merge("a", "c")    # not adjacent
    with pytest.raises(InvalidArgument):
        ShardMap.from_bounds([("x", None, b"m"), ("y", b"n", None)])  # gap


def test_shard_map_uniform_covers_keyspace():
    m = ShardMap.uniform(4)
    assert len(m.shards) == 4
    for key in (b"\x00" * 16, b"\x3f" + b"\xaa" * 15, b"\x80" * 16,
                b"\xff" * 16):
        assert m.shard_for(key) is not None
    assert m.shard_for(b"\x00" * 16).name == "s0"
    assert m.shard_for(b"\xff" * 16).name == "s3"


# -- routing + tokens --------------------------------------------------------


def test_router_routes_tokens_multiget_scan(tmp_path):
    stats = Statistics()
    r = cluster(tmp_path, stats)
    try:
        rows = {b"apple": b"1", b"kiwi": b"2", b"mango": b"3",
                b"zebra": b"4"}
        tokens = {k: r.put(k, v) for k, v in rows.items()}
        assert tokens[b"apple"].shard == "a"
        assert tokens[b"mango"].shard == "b"
        for k, v in rows.items():
            assert r.get(k, token=tokens[k]) == v
        assert r.multi_get(list(rows)) == list(rows.values())
        assert dict(r.scan()) == rows
        assert dict(r.scan(begin=b"k", end=b"n")) == {
            b"kiwi": b"2", b"mango": b"3"}
        assert stats.get_ticker_count(st.SHARD_ROUTED_WRITES) == 4
        assert stats.get_ticker_count(st.SHARD_ROUTED_READS) > 0
        # delete routes too
        r.delete(b"kiwi")
        assert r.get(b"kiwi") is None
    finally:
        r.close()


def test_cross_shard_batch_write_and_range_delete(tmp_path):
    r = cluster(tmp_path)
    try:
        b = WriteBatch()
        b.put(b"alpha", b"1")
        b.put(b"zeta", b"2")
        b.delete(b"nope")
        toks = r.write(b)
        assert sorted(t.shard for t in toks) == ["a", "b"]
        assert r.get(b"alpha") == b"1" and r.get(b"zeta") == b"2"
        # range deletion spanning the shard boundary is clipped per shard
        b2 = WriteBatch()
        b2.delete_range(b"a", b"zz")
        r.write(b2)
        assert r.get(b"alpha") is None and r.get(b"zeta") is None
    finally:
        r.close()


class _PoisonReplica:
    """A 'follower' that claims to have applied everything and serves a
    poison value: any read it serves is by definition stale-served."""

    def __init__(self):
        self.reads = 0

    def applied_sequence(self):
        return 1 << 60

    def get(self, key, opts=None, cf=None):
        self.reads += 1
        return b"STALE"

    def multi_get(self, keys, opts=None, cf=None):
        self.reads += 1
        return [b"STALE"] * len(keys)


def test_split_invalidates_tokens_and_never_serves_stale(tmp_path):
    stats = Statistics()
    r = cluster(tmp_path, stats)
    try:
        poison = _PoisonReplica()
        r.add_follower("a", poison)
        tok = r.put(b"apple", b"fresh")
        # Epoch matches: the follower (claiming applied>=token) serves.
        assert r.get(b"apple", token=tok) == b"STALE"
        assert poison.reads == 1

        r.split_shard("a", b"f")
        # Pre-split token: shard 'a' epoch advanced → token rejected and
        # the read re-routes to the primary; the poisoned follower is
        # NEVER consulted again with this token.
        assert r.get(b"apple", token=tok) == b"fresh"
        assert poison.reads == 1
        assert stats.get_ticker_count(st.SHARD_TOKEN_REJECTS) >= 1
        # A fresh post-split token round-trips normally.
        tok2 = r.put(b"apple", b"fresher")
        assert tok2.epoch == r.map.get("a").epoch
        assert r.get(b"apple", token=tok2) in (b"fresher", b"STALE")
    finally:
        r.close()


def test_replica_router_epoch_token_fix(tmp_path):
    """The satellite at the replication layer: StalenessToken carries an
    epoch; advancing the epoch re-routes token reads to the primary."""
    stats = Statistics()
    db = DB.open(str(tmp_path / "p"), opts(statistics=stats))
    try:
        epoch_box = [7]
        rr = ReplicaRouter(db, statistics=stats,
                           epoch_provider=lambda: epoch_box[0])
        poison = _PoisonReplica()
        rr.add_follower(poison)
        seq = rr.put(b"k", b"real")
        tok = rr.token(seq)
        assert tok == StalenessToken(seq=seq, epoch=7)
        assert rr.get(b"k", token=tok) == b"STALE"  # follower eligible
        epoch_box[0] = 8  # replica-set epoch advanced
        assert rr.get(b"k", token=tok) == b"real"   # primary, not stale
        assert stats.get_ticker_count(st.ROUTER_EPOCH_REJECTS) == 1
        # bare int tokens keep their legacy meaning
        assert rr.get(b"k", token=seq) == b"STALE"
    finally:
        db.close()


# -- write stalls ------------------------------------------------------------


def test_write_stall_state_and_metrics(tmp_path):
    stats = Statistics()
    db = DB.open(str(tmp_path / "d"),
                 opts(statistics=stats, level0_slowdown_writes_trigger=1,
                      level0_stop_writes_trigger=100,
                      level0_file_num_compaction_trigger=64))
    try:
        assert db.write_stall_state()["state"] == "none"
        db.put(b"a", b"1")
        db.flush()
        db.put(b"b", b"2")
        db.flush()
        s = db.write_stall_state()
        assert s["state"] == "delayed" and s["l0_files"] >= 1
        assert s["drainable"] is True
        db.put(b"c", b"3")  # rides the delay ramp
        assert stats.get_ticker_count(st.STALL_MICROS) > 0
        assert stats.get_ticker_count(st.WRITE_STALL_COUNT) >= 1
        assert stats.get_histogram(st.WRITE_STALL_MICROS_HIST).count >= 1
        assert db.write_stall_state()["stalls"] >= 1

        from toplingdb_tpu.utils.config import SidePluginRepo

        repo = SidePluginRepo()
        repo.attach_db("d", db)
        port = repo.start_http()
        try:
            with urllib.request.urlopen(
                    f"http://127.0.0.1:{port}/metrics") as resp:
                text = resp.read().decode()
            assert 'tpulsm_write_stall_state{db="d"} 1' in text
            assert "tpulsm_write_stall_l0_files" in text
        finally:
            repo.stop_http()
    finally:
        db.close()


def test_stall_state_not_drainable_when_auto_compaction_off(tmp_path):
    db = DB.open(str(tmp_path / "d"),
                 opts(disable_auto_compactions=True,
                      level0_slowdown_writes_trigger=1))
    try:
        db.put(b"a", b"1")
        db.flush()
        db.put(b"b", b"2")
        db.flush()
        s = db.write_stall_state()
        # Nothing can drain L0 → writes are never stalled → state "none".
        assert s["drainable"] is False and s["state"] == "none"
    finally:
        db.close()


# -- admission control -------------------------------------------------------


def test_admission_rate_limit_and_stall_shed():
    stats = Statistics()
    adm = AdmissionController(statistics=stats)
    adm.set_quota("hot", TenantQuota(write_ops_per_sec=50, max_wait=0.0))
    # unlimited tenant is never shed
    for _ in range(100):
        adm.admit_write("cold", 100, stall_state="stopped")
    shed = 0
    for _ in range(100):
        try:
            adm.admit_write("hot", 100)
        except Busy:
            shed += 1
    assert shed > 0
    assert stats.get_ticker_count(st.SHARD_WRITES_SHED) == shed
    # stall shedding: zero-wait denial once the bucket is empty
    adm.set_quota("h2", TenantQuota(write_ops_per_sec=5, max_wait=2.0))
    for _ in range(5):
        adm.admit_write("h2", 1)
    import time as _t

    t0 = _t.monotonic()
    with pytest.raises(Busy):
        adm.admit_write("h2", 1, stall_state="stopped")
    assert _t.monotonic() - t0 < 0.5  # did NOT wait out max_wait


def test_router_sheds_hot_tenant_siblings_unaffected(tmp_path):
    stats = Statistics()
    adm = AdmissionController(statistics=stats)
    adm.set_quota("hot", TenantQuota(write_ops_per_sec=20, max_wait=0.0))
    r = cluster(tmp_path, stats, admission=adm)
    try:
        # the hot tenant's shard is reported stall-stopped: shed, not queue
        r._serving("a").primary.write_stall_state = lambda: {
            "state": "stopped"}
        shed = served = 0
        for i in range(80):
            try:
                r.put(b"a%04d" % i, b"x", tenant="hot")
                served += 1
            except Busy:
                shed += 1
        assert shed > 0
        # sibling shard, different tenant: every write lands
        for i in range(50):
            r.put(b"z%04d" % i, b"y", tenant="sib")
        assert r.get(b"z0000") == b"y"
        assert stats.get_ticker_count(st.SHARD_WRITES_SHED) == shed
    finally:
        r.close()


# -- migration ---------------------------------------------------------------


def test_migration_moves_shard_and_bumps_epoch(tmp_path):
    stats = Statistics()
    r = cluster(tmp_path, stats)
    try:
        for i in range(300):
            r.put(b"m%05d" % i, b"v%d" % i)   # shard b
            r.put(b"a%05d" % i, b"w%d" % i)   # shard a
        pre_tok = r.put(b"m99999", b"pre")
        old_primary = r._serving("b").primary
        old_epoch = r.map.get("b").epoch

        out = ShardMigration(r, "b", str(tmp_path / "b-new")).run()
        assert out["shard"] == "b"
        assert r.map.get("b").epoch > old_epoch
        assert r._serving("b").primary is not old_primary
        # data moved: reads hit the new instance
        assert r.get(b"m00042") == b"v42"
        assert r.get(b"m99999") == b"pre"
        # pre-migration token is rejected (re-routed), value still right
        before = stats.get_ticker_count(st.SHARD_TOKEN_REJECTS)
        assert r.get(b"m99999", token=pre_tok) == b"pre"
        assert stats.get_ticker_count(st.SHARD_TOKEN_REJECTS) == before + 1
        # shard a untouched
        assert r.get(b"a00042") == b"w42"
        assert stats.get_ticker_count(st.SHARD_MIGRATIONS) == 1
        # writes keep flowing to the new primary
        t = r.put(b"m00042", b"v42b")
        assert t.epoch == r.map.get("b").epoch
        assert r.get(b"m00042", token=t) == b"v42b"
        old_primary.close()  # retired source instance
    finally:
        r.close()


def test_migration_abort_leaves_source_serving(tmp_path):
    r = cluster(tmp_path)
    try:
        for i in range(50):
            r.put(b"m%05d" % i, b"v%d" % i)

        def kaboom(phase):
            if phase == "cutover":
                raise RuntimeError("injected kill at cutover")

        with pytest.raises(MigrationAborted):
            ShardMigration(r, "b", str(tmp_path / "b-new"),
                           fault_hook=kaboom).run()
        # fence lifted, source authoritative, writes flow
        assert r.map.get("b").state == "serving"
        assert not r._gate("b").fenced
        r.put(b"m00000", b"after")
        assert r.get(b"m00000") == b"after"
    finally:
        r.close()


def test_fence_recovery_after_hard_kill(tmp_path):
    """A migration hard-killed between fence and cutover leaves the gate
    closed; ShardMigration.recover is the supervisor-side cleanup."""
    r = cluster(tmp_path, fence_timeout=0.2)
    try:
        r.put(b"m1", b"v1")
        r.fence_shard("b")
        with pytest.raises(Busy):
            r.put(b"m2", b"v2")
        ShardMigration.recover(r, "b")
        r.put(b"m2", b"v2")
        assert r.get(b"m2") == b"v2"
    finally:
        r.close()


def test_chaos_soak_kill_mid_migration_converges(tmp_path):
    """The acceptance soak: concurrent writers, a shard migration under
    30% drop/delay/truncate ship faults, a kill mid-migration, recovery,
    and a retried migration — the cluster must converge to byte parity
    with the merged oracle: no lost keys, no double-served keys, and no
    token ever served from a pre-migration epoch."""
    stats = Statistics()
    r = cluster(tmp_path, stats)
    oracle: dict[bytes, bytes] = {}
    olock = threading.Lock()
    stop = threading.Event()
    errors: list = []

    def writer(wid: int):
        # Disjoint per-writer key spaces: oracle order == DB order.
        rng = random.Random(1000 + wid)
        i = 0
        spaces = (b"a", b"m", b"t")  # both shards, including the moving one
        while not stop.is_set():
            p = spaces[rng.randrange(3)]
            k = b"%s.w%d.%04d" % (p, wid, rng.randrange(800))
            v = b"v%d.%d" % (wid, i)
            try:
                r.put(k, v, tenant=f"w{wid}")
            except Busy:
                continue  # fence window: retry later
            except Exception as e:  # noqa: BLE001
                errors.append(e)
                return
            with olock:
                oracle[k] = v
            i += 1

    threads = [threading.Thread(target=writer, args=(w,)) for w in (0, 1)]
    for t in threads:
        t.start()
    try:
        # warm up some traffic, then attempt a migration that gets killed
        # mid-catchup while the transport injects 30% faults
        import time as _t

        _t.sleep(0.3)
        # Pinned drops on the first two pulls guarantee the catch-up needs
        # a 3rd round (writers keep the source moving), so the kill point
        # is deterministically MID-catchup, after real shipping started.
        inj = ShipFaultInjector(schedule={0: "drop", 1: "truncate"},
                                rate=0.3, seed=7, delay_sec=0.002)
        rounds = [0]

        def kill_mid_catchup(phase):
            if phase == "catchup":
                rounds[0] += 1
                if rounds[0] == 3:
                    raise RuntimeError("kill -9 (simulated) mid-catchup")

        with pytest.raises(MigrationAborted):
            ShardMigration(
                r, "b", str(tmp_path / "b-try1"),
                transport_factory=lambda t: FaultyTransport(t, inj),
                catchup_lag=0, fault_hook=kill_mid_catchup).run()
        assert stats.get_ticker_count(st.SHARD_MIGRATION_FAILURES) == 1
        assert inj.injected, "chaos plan never fired"
        # cluster still serving through the abort
        tok = r.put(b"m.probe", b"alive")
        with olock:
            oracle[b"m.probe"] = b"alive"
        assert r.get(b"m.probe", token=tok) == b"alive"

        # retry under the same fault rate — this one must complete
        pre_tok = tok
        inj2 = ShipFaultInjector(rate=0.3, seed=11, delay_sec=0.002)
        out = ShardMigration(
            r, "b", str(tmp_path / "b-try2"),
            transport_factory=lambda t: FaultyTransport(t, inj2),
            catchup_lag=100, catchup_timeout=120.0).run()
        assert out["shard"] == "b"
        _t.sleep(0.3)  # post-cutover traffic onto the new primary
    finally:
        stop.set()
        for t in threads:
            t.join(timeout=30)
    assert not errors, errors

    # -- convergence: merged-oracle parity, exactly-once serving ----------
    scanned = list(r.scan())
    keys = [k for k, _ in scanned]
    assert len(keys) == len(set(keys)), "double-served keys"
    assert dict(scanned) == oracle, (
        f"lost/extra keys: {len(scanned)} scanned vs {len(oracle)} oracle")
    # every key individually readable through the router
    sample = random.Random(3).sample(sorted(oracle), min(64, len(oracle)))
    assert r.multi_get(sample) == [oracle[k] for k in sample]
    # pre-migration token can never be served under its old epoch
    before = stats.get_ticker_count(st.SHARD_TOKEN_REJECTS)
    assert r.get(b"m.probe", token=pre_tok) == b"alive"
    assert stats.get_ticker_count(st.SHARD_TOKEN_REJECTS) == before + 1
    r.close()


# -- balancer ----------------------------------------------------------------


def test_balancer_splits_big_and_merges_cold(tmp_path):
    r = cluster(tmp_path)
    try:
        for i in range(2000):
            r.put(b"a%06d" % i, b"v" * 100)
        r._serving("a").primary.flush()
        bal = ShardBalancer(r, BalancerOptions(split_bytes=10_000,
                                               merge_bytes=0))
        actions = bal.run_once()
        assert any(a["action"] == "split" and a["shard"] == "a"
                   for a in actions)
        key = bytes.fromhex(
            next(a for a in actions if a["action"] == "split")
            ["split_key_hex"])
        assert b"a000000" < key < b"a002000"
        assert len(r.map.names()) == 3
        # both halves still serve (shared stack until migrated)
        assert r.get(b"a000000") == b"v" * 100
        assert r.get(b"a001999") == b"v" * 100
        # cold adjacent same-backend shards merge back
        bal2 = ShardBalancer(r, BalancerOptions(split_bytes=1 << 40,
                                                merge_bytes=1 << 40))
        acts2 = bal2.run_once()
        assert any(a["action"] == "merge" for a in acts2)
        assert r.get(b"a000000") == b"v" * 100
    finally:
        r.close()


# -- HTTP control plane + CLI ------------------------------------------------


def test_shards_http_view_and_admin_cli(tmp_path, capsys):
    from toplingdb_tpu.tools import shard_admin
    from toplingdb_tpu.utils.config import SidePluginRepo

    stats = Statistics()
    r = cluster(tmp_path, stats)
    repo = SidePluginRepo()
    repo.attach_cluster("c1", r)
    port = repo.start_http()
    base = f"http://127.0.0.1:{port}"
    try:
        for i in range(100):
            r.put(b"a%04d" % i, b"v")

        with urllib.request.urlopen(f"{base}/shards") as resp:
            assert json.loads(resp.read()) == {"clusters": ["c1"]}
        with urllib.request.urlopen(f"{base}/shards/c1") as resp:
            view = json.loads(resp.read())
        assert view["n_shards"] == 2
        assert view["map"]["shards"][0]["name"] == "a"
        assert view["shards"][0]["traffic"]["writes"] == 100

        # /metrics carries the cluster gauges + SHARD_* tickers
        with urllib.request.urlopen(f"{base}/metrics") as resp:
            text = resp.read().decode()
        assert 'tpulsm_shard_epoch{cluster="c1",shard="a"}' in text
        assert "tpulsm_shard_routed_writes" in text

        # POST split via the CLI
        rc = shard_admin.main(["--url", base, "split", "--cluster", "c1",
                               "--shard", "a", "--key", "a0050"])
        assert rc == 0
        out = json.loads(capsys.readouterr().out)
        assert out["ok"] and out["left"]["name"] == "a"
        assert len(r.map.names()) == 3

        # status CLI renders the table
        assert shard_admin.main(["--url", base, "status",
                                 "--cluster", "c1"]) == 0
        text = capsys.readouterr().out
        assert "map_version=" in text and "epoch=" in text

        # migrate the split-off half to its own instance via the CLI
        dest = str(tmp_path / "right-new")
        right = r.map.names()[1]
        rc = shard_admin.main(["--url", base, "migrate", "--cluster", "c1",
                               "--shard", right, "--dest", dest])
        assert rc == 0
        out = json.loads(capsys.readouterr().out)
        assert out["ok"] and out["migration"]["dest"] == dest
        assert r.get(b"a0075") == b"v"

        # bad requests are client errors, not crashes
        req = urllib.request.Request(
            f"{base}/shards/c1/split", data=b"{}",
            headers={"Content-Type": "application/json"}, method="POST")
        with pytest.raises(urllib.error.HTTPError) as ei:
            urllib.request.urlopen(req)
        assert ei.value.code == 400
    finally:
        repo.stop_http()
        r.close()


def test_shard_map_save_load(tmp_path):
    m = ShardMap.uniform(4)
    m.split("s1", b"\x50" + b"\x00" * 15)
    path = str(tmp_path / "shardmap.json")
    m.save(path)
    m2 = ShardMap.load(path)
    assert m2.to_config() == m.to_config()


def test_shard_map_save_is_crash_atomic(tmp_path):
    """Crash twin for ShardMap.save: a process killed mid-save leaves a
    torn `.tmp` side file — never a torn map. The complete OLD map must
    survive, and a later save must atomically replace both."""
    import json as _json

    path = str(tmp_path / "shardmap.json")
    old = ShardMap.uniform(2)
    old.save(path)

    # Crash mid-save: the new map's bytes were only partially written to
    # the side file when the process died (save() goes tmp → fsync →
    # rename, so `path` itself was never touched).
    new = ShardMap.uniform(2)
    new.split("s0", b"\x40" + b"\x00" * 15)
    torn = _json.dumps(new.to_config(), indent=1).encode()[:37]
    with open(path + ".tmp", "wb") as f:
        f.write(torn)

    loaded = ShardMap.load(path)  # readers ignore stray .tmp files
    assert loaded.to_config() == old.to_config()

    # Retrying the save replaces the torn residue and the old map in one
    # atomic step; nothing is left behind.
    new.save(path)
    assert ShardMap.load(path).to_config() == new.to_config()
    assert not (tmp_path / "shardmap.json.tmp").exists()


def test_check_telemetry_lint_covers_shard_names():
    """The new SHARD_* tickers and shard.* spans must satisfy the tier-1
    telemetry lint (names declared / span table rows present)."""
    from toplingdb_tpu.tools import check_telemetry

    assert check_telemetry.run() == []
