"""Wide columns under the dedicated value type (reference
kTypeWideColumnEntity, db/dbformat.h + db/wide/): typed detection (no
magic-sniff ambiguity on plain binary values), flush/compaction
survival, entity-aware merge, iterator columns() parity."""

import pytest

from toplingdb_tpu.db.db import DB
from toplingdb_tpu.db.wide_columns import _MAGIC
from toplingdb_tpu.options import Options
from toplingdb_tpu.utils.merge_operator import StringAppendOperator


@pytest.fixture
def dbp(tmp_path):
    return str(tmp_path / "db")


def test_plain_value_with_magic_prefix_is_not_reinterpreted(dbp):
    """The r04 ADVICE case: an arbitrary binary value that happens to
    start with \\x00WCE1 and parse as an entity must come back VERBATIM."""
    db = DB.open(dbp, Options(create_if_missing=True))
    # _MAGIC + varint32(0) parses as an empty entity under the sniff.
    tricky = _MAGIC + b"\x00"
    db.put(b"k", tricky)
    assert db.get(b"k") == tricky
    assert db.multi_get([b"k"]) == [tricky]
    it = db.new_iterator()
    it.seek_to_first()
    assert it.value() == tricky
    db.flush()
    db.wait_for_compactions()
    assert db.get(b"k") == tricky
    db.close()


def test_entity_get_unwraps_default_column(dbp):
    db = DB.open(dbp, Options(create_if_missing=True))
    db.put_entity(b"e", {b"": b"dflt", b"name": b"alice"})
    assert db.get(b"e") == b"dflt"
    assert db.get_entity(b"e") == {b"": b"dflt", b"name": b"alice"}
    assert db.multi_get([b"e"]) == [b"dflt"]
    db.close()


def test_entity_survives_flush_and_compaction(dbp):
    db = DB.open(dbp, Options(create_if_missing=True))
    for i in range(500):
        db.put_entity(b"e%04d" % i, {b"": b"d%d" % i, b"c": b"x" * 50})
    db.flush()
    db.compact_range(None, None)
    db.wait_for_compactions()
    assert db.get(b"e0007") == b"d7"
    assert db.get_entity(b"e0499") == {b"": b"d499", b"c": b"x" * 50}
    db.close()
    db = DB.open(dbp, Options())  # recovery keeps the type
    assert db.get(b"e0007") == b"d7"
    db.close()


def test_iterator_columns_and_value(dbp):
    db = DB.open(dbp, Options(create_if_missing=True))
    db.put(b"a", b"plain")
    db.put_entity(b"b", {b"": b"bd", b"col": b"cv"})
    it = db.new_iterator()
    it.seek_to_first()
    assert it.key() == b"a" and it.value() == b"plain"
    assert it.columns() == {b"": b"plain"}
    it.next()
    assert it.key() == b"b" and it.value() == b"bd"
    assert it.columns() == {b"": b"bd", b"col": b"cv"}
    it.prev()
    assert it.value() == b"plain"
    db.close()


def test_merge_over_entity_folds_default_column(dbp):
    db = DB.open(dbp, Options(create_if_missing=True,
                              merge_operator=StringAppendOperator(b",")))
    db.put_entity(b"m", {b"": b"base", b"keep": b"k"})
    db.merge(b"m", b"x")
    db.merge(b"m", b"y")
    # Get path
    assert db.get(b"m") == b"base,x,y"
    assert db.get_entity(b"m") == {b"": b"base,x,y", b"keep": b"k"}
    # Iterator path
    it = db.new_iterator()
    it.seek(b"m")
    assert it.value() == b"base,x,y"
    assert it.columns() == {b"": b"base,x,y", b"keep": b"k"}
    # Compaction path: fold down to one entity entry
    db.flush()
    db.compact_range(None, None)
    db.wait_for_compactions()
    assert db.get(b"m") == b"base,x,y"
    assert db.get_entity(b"m") == {b"": b"base,x,y", b"keep": b"k"}
    db.close()


def test_single_delete_annihilates_entity(dbp):
    db = DB.open(dbp, Options(create_if_missing=True))
    db.put_entity(b"s", {b"": b"v"})
    db.single_delete(b"s")
    db.flush()
    db.compact_range(None, None)
    db.wait_for_compactions()
    assert db.get(b"s") is None
    db.close()


def test_entity_in_non_default_cf_and_parsed_path(dbp):
    """Entity records must survive the PARSED WriteBatch path (non-simple
    batches: CF-prefixed records decode through entries_cf)."""
    from toplingdb_tpu.db.write_batch import WriteBatch
    from toplingdb_tpu.db.wide_columns import encode_entity

    db = DB.open(dbp, Options(create_if_missing=True))
    cf = db.create_column_family("wide")
    b = WriteBatch()
    b.put_entity(b"ek", encode_entity({b"": b"cfd", b"c": b"v"}),
                 cf=db._cf_id(cf))
    assert list(b.entries_cf())  # decodes, no Corruption
    db.write(b)
    assert db.get(b"ek", cf=cf) == b"cfd"
    assert db.get_entity(b"ek", cf=cf) == {b"": b"cfd", b"c": b"v"}
    db.close()


def test_legacy_unwrap_gate(dbp):
    """Pre-type databases stored entities as VALUE + magic; the gate
    restores the old presentation for them."""
    from toplingdb_tpu.db.wide_columns import encode_entity

    db = DB.open(dbp, Options(create_if_missing=True))
    db.put(b"old", encode_entity({b"": b"legacy-default"}))  # r4-style
    db.close()
    db = DB.open(dbp, Options(legacy_wide_column_unwrap=True))
    assert db.get(b"old") == b"legacy-default"
    db.close()
    db = DB.open(dbp, Options())  # gate off: raw bytes come back
    assert db.get(b"old") == encode_entity({b"": b"legacy-default"})
    db.close()
