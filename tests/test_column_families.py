"""Column families: isolation, atomic cross-CF batches, recovery, drop,
compaction per CF (reference column_family_test.cc shape)."""

import pytest

from toplingdb_tpu.db.db import DB
from toplingdb_tpu.db.write_batch import WriteBatch
from toplingdb_tpu.options import Options
from toplingdb_tpu.utils.status import Status


def opts(**kw):
    kw.setdefault("write_buffer_size", 8 * 1024)
    return Options(**kw)


def test_cf_isolation(tmp_db_path):
    with DB.open(tmp_db_path, opts()) as db:
        users = db.create_column_family("users")
        posts = db.create_column_family("posts")
        db.put(b"k", b"default-v")
        db.put(b"k", b"users-v", cf=users)
        db.put(b"k", b"posts-v", cf=posts)
        assert db.get(b"k") == b"default-v"
        assert db.get(b"k", cf=users) == b"users-v"
        assert db.get(b"k", cf=posts) == b"posts-v"
        db.delete(b"k", cf=users)
        assert db.get(b"k", cf=users) is None
        assert db.get(b"k") == b"default-v"


def test_cf_atomic_batch(tmp_db_path):
    with DB.open(tmp_db_path, opts()) as db:
        a = db.create_column_family("a")
        b = WriteBatch()
        b.put(b"x", b"1")
        b.put(b"y", b"2", cf=a.id)
        b.delete(b"x", cf=a.id)
        db.write(b)
        assert db.get(b"x") == b"1"
        assert db.get(b"y", cf=a) == b"2"
        assert db.get(b"x", cf=a) is None


def test_cf_survive_reopen_with_flush_and_wal(tmp_db_path):
    with DB.open(tmp_db_path, opts()) as db:
        logs = db.create_column_family("logs")
        for i in range(500):
            db.put(b"k%04d" % i, b"d%04d" % i)
            db.put(b"k%04d" % i, b"l%04d" % i, cf=logs)
        db.flush()
        db.put(b"wal-only", b"dv")
        db.put(b"wal-only", b"lv", cf=logs)
        # No clean close: simulate crash.
        db._wal.sync()
        db._closed = True
    with DB.open(tmp_db_path, opts()) as db:
        logs = db.get_column_family("logs")
        assert logs is not None
        assert db.get(b"k0100") == b"d0100"
        assert db.get(b"k0100", cf=logs) == b"l0100"
        assert db.get(b"wal-only") == b"dv"
        assert db.get(b"wal-only", cf=logs) == b"lv"


def test_cf_iterators_are_per_cf(tmp_db_path):
    with DB.open(tmp_db_path, opts()) as db:
        aux = db.create_column_family("aux")
        db.put(b"d1", b"1")
        db.put(b"a1", b"2", cf=aux)
        it = db.new_iterator()
        it.seek_to_first()
        assert [k for k, _ in it.entries()] == [b"d1"]
        it = db.new_iterator(cf=aux)
        it.seek_to_first()
        assert [k for k, _ in it.entries()] == [b"a1"]


def test_cf_compaction_independent(tmp_db_path):
    with DB.open(tmp_db_path, opts()) as db:
        big = db.create_column_family("big")
        for i in range(4000):
            db.put(b"key%05d" % (i % 1000), b"v%07d" % i, cf=big)
        db.put(b"small", b"1")
        db.flush()
        db.compact_range()
        db.wait_for_compactions()
        assert db.get(b"small") == b"1"
        for k in range(0, 1000, 83):
            last = max(i for i in range(k, 4000, 1000))
            assert db.get(b"key%05d" % k, cf=big) == b"v%07d" % last
        vbig = db.versions.cf_current(big.id)
        assert sum(f.num_entries for _, f in vbig.all_files()) == 1000


def test_cf_drop(tmp_db_path):
    with DB.open(tmp_db_path, opts()) as db:
        tmp = db.create_column_family("tmp")
        db.put(b"k", b"v", cf=tmp)
        db.flush()
        db.drop_column_family(tmp)
        with pytest.raises(Status):
            db.get(b"k", cf=tmp)
    with DB.open(tmp_db_path, opts()) as db:
        assert db.get_column_family("tmp") is None


def test_cf_name_reuse_after_drop(tmp_db_path):
    with DB.open(tmp_db_path, opts()) as db:
        c1 = db.create_column_family("c")
        db.put(b"k", b"old", cf=c1)
        db.flush()
        db.drop_column_family(c1)
        c2 = db.create_column_family("c")
        assert c2.id != c1.id
        assert db.get(b"k", cf=c2) is None  # fresh keyspace
    with DB.open(tmp_db_path, opts()) as db:
        c = db.get_column_family("c")
        assert db.get(b"k", cf=c) is None


def test_checkpoint_includes_all_cfs(tmp_db_path, tmp_path):
    """Review regression: checkpoint must snapshot every CF."""
    from toplingdb_tpu.utilities.checkpoint import create_checkpoint

    dst = str(tmp_path / "ckpt")
    with DB.open(tmp_db_path, opts()) as db:
        aux = db.create_column_family("aux")
        db.put(b"d", b"1")
        db.put(b"a", b"2", cf=aux)
        create_checkpoint(db, dst)
    with DB.open(dst, opts()) as db2:
        aux2 = db2.get_column_family("aux")
        assert aux2 is not None
        assert db2.get(b"d") == b"1"
        assert db2.get(b"a", cf=aux2) == b"2"


def test_readonly_db_respects_cfs(tmp_db_path):
    """Review regression: RO WAL replay must not bleed CFs together."""
    from toplingdb_tpu.db.db_readonly import ReadOnlyDB

    with DB.open(tmp_db_path, opts()) as db:
        aux = db.create_column_family("aux")
        db.put(b"k", b"default-v")
        db.put(b"k", b"aux-v", cf=aux)
    ro = ReadOnlyDB.open(tmp_db_path)
    assert ro.get(b"k") == b"default-v"
    aux_ro = ro.get_column_family("aux")
    assert ro.get(b"k", cf=aux_ro) == b"aux-v"
    ro.close()


def test_drop_cf_with_inflight_compaction_edit(tmp_db_path):
    """Review regression: a version edit for a dropped CF is discarded, not a
    KeyError."""
    from toplingdb_tpu.db.version_edit import VersionEdit

    with DB.open(tmp_db_path, opts()) as db:
        aux = db.create_column_family("aux")
        db.put(b"x", b"1", cf=aux)
        db.flush()
        db.drop_column_family(aux)
        # Simulate the in-flight job's install after the drop.
        db.versions.log_and_apply(VersionEdit(column_family=aux.id))
        db.put(b"ok", b"1")
        assert db.get(b"ok") == b"1"


def test_double_drop_raises_cleanly(tmp_db_path):
    from toplingdb_tpu.utils.status import InvalidArgument

    with DB.open(tmp_db_path, opts()) as db:
        aux = db.create_column_family("aux")
        db.drop_column_family(aux)
        with pytest.raises(InvalidArgument):
            db.versions.drop_column_family(aux.id)
