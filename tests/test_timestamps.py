"""User-defined timestamps (the reference's TOPLINGDB_WITH_TIMESTAMP
feature: BytewiseComparatorWithU64TsWrapper, ReadOptions.timestamp,
full_history_ts_low trimming)."""

import pytest

from toplingdb_tpu.db import dbformat
from toplingdb_tpu.db.db import DB
from toplingdb_tpu.db.dbformat import U64_TS_BYTEWISE, decode_ts, encode_ts
from toplingdb_tpu.options import Options, ReadOptions
from toplingdb_tpu.utils.status import InvalidArgument


@pytest.fixture
def db(tmp_path):
    d = DB.open(str(tmp_path / "db"), Options(comparator=U64_TS_BYTEWISE))
    yield d
    d.close()


def test_ts_encoding_orders_descending():
    # newer ts → suffix sorts FIRST (raw bytewise)
    assert encode_ts(9) < encode_ts(5) < encode_ts(0)
    for ts in (0, 1, 12345, 2**63, 2**64 - 1):
        assert decode_ts(encode_ts(ts)) == ts


def test_ts_required_and_rejected(db, tmp_path):
    with pytest.raises(InvalidArgument):
        db.put(b"k", b"v")  # ts required
    plain = DB.open(str(tmp_path / "plain"), Options())
    with pytest.raises(InvalidArgument):
        plain.put(b"k", b"v", ts=5)  # no ts comparator
    with pytest.raises(InvalidArgument):
        plain.get(b"k", ReadOptions(timestamp=5))
    plain.close()


def test_read_as_of_timestamp(db):
    db.put(b"k", b"v@10", ts=10)
    db.put(b"k", b"v@20", ts=20)
    db.put(b"k", b"v@30", ts=30)
    assert db.get(b"k") == b"v@30"                          # latest
    assert db.get(b"k", ReadOptions(timestamp=25)) == b"v@20"
    assert db.get(b"k", ReadOptions(timestamp=10)) == b"v@10"
    assert db.get(b"k", ReadOptions(timestamp=9)) is None   # before history
    v, ts = db.get_with_ts(b"k", ReadOptions(timestamp=25))
    assert (v, ts) == (b"v@20", 20)


def test_delete_at_timestamp(db):
    db.put(b"k", b"alive", ts=10)
    db.delete(b"k", ts=20)
    db.put(b"k", b"reborn", ts=30)
    assert db.get(b"k", ReadOptions(timestamp=15)) == b"alive"
    assert db.get(b"k", ReadOptions(timestamp=25)) is None
    assert db.get(b"k") == b"reborn"


def test_iterate_as_of_ts_with_deletions(db):
    db.put(b"a", b"a@10", ts=10)
    db.put(b"b", b"b@10", ts=10)
    db.delete(b"b", ts=20)
    db.put(b"c", b"c@30", ts=30)
    it = db.new_iterator(ReadOptions(timestamp=25))
    it.seek_to_first()
    got = [(k, v) for k, v in it.entries()]
    assert got == [(b"a", b"a@10")]  # b deleted at 20, c not yet written
    it = db.new_iterator(ReadOptions(timestamp=15))
    it.seek_to_first()
    assert [(k, v) for k, v in it.entries()] == [
        (b"a", b"a@10"), (b"b", b"b@10")
    ]
    it = db.new_iterator(ReadOptions())
    it.seek_to_first()
    assert [(k, v) for k, v in it.entries()] == [
        (b"a", b"a@10"), (b"c", b"c@30")
    ]
    assert it is not None


def test_iterator_timestamp_accessor_and_backward(db):
    db.put(b"x", b"x@5", ts=5)
    db.put(b"y", b"y@7", ts=7)
    it = db.new_iterator(ReadOptions())
    it.seek(b"x")
    assert it.valid() and it.key() == b"x" and it.timestamp() == 5
    it.seek_to_last()
    assert it.key() == b"y" and it.value() == b"y@7" and it.timestamp() == 7
    it.prev()
    assert it.key() == b"x"
    it.seek_for_prev(b"xzz")
    assert it.key() == b"x"


def test_ts_survives_flush_compact_reopen(tmp_path):
    db = DB.open(str(tmp_path / "db"), Options(comparator=U64_TS_BYTEWISE))
    for i in range(100):
        db.put(b"k%03d" % i, b"old%d" % i, ts=10)
    db.flush()
    for i in range(0, 100, 2):
        db.put(b"k%03d" % i, b"new%d" % i, ts=20)
    db.flush()
    db.compact_range()
    assert db.get(b"k000", ReadOptions(timestamp=15)) == b"old0"
    assert db.get(b"k000") == b"new0"
    db.close()
    db = DB.open(str(tmp_path / "db"), Options(comparator=U64_TS_BYTEWISE))
    assert db.get(b"k002", ReadOptions(timestamp=12)) == b"old2"
    assert db.get(b"k001") == b"old1"
    db.close()


def test_full_history_ts_low_trims_compaction(tmp_path):
    db = DB.open(str(tmp_path / "db"), Options(comparator=U64_TS_BYTEWISE))
    db.put(b"k", b"v@10", ts=10)
    db.put(b"k", b"v@20", ts=20)
    db.put(b"k", b"v@30", ts=30)
    db.flush()
    db.increase_full_history_ts_low(25)
    with pytest.raises(InvalidArgument):
        db.increase_full_history_ts_low(5)  # monotonic
    db.compact_range()
    # versions below ts_low collapsed to the newest one (ts=20 survives as
    # the value visible at ts_low; ts=10 dropped)
    assert db.get(b"k", ReadOptions(timestamp=26)) == b"v@20"
    assert db.get(b"k") == b"v@30"
    # reads below the trim point are rejected, not silently wrong
    with pytest.raises(InvalidArgument):
        db.new_iterator(ReadOptions(timestamp=10))
    db.close()


def test_tombstone_not_dropped_at_bottommost(tmp_path):
    """A ts tombstone shadows older-ts versions in OTHER groups; bottommost
    compaction must not drop it (regression: delete resurrection)."""
    db = DB.open(str(tmp_path / "db"), Options(comparator=U64_TS_BYTEWISE))
    db.put(b"k", b"old", ts=3)
    db.delete(b"k", ts=5)
    db.flush()
    db.compact_range()
    assert db.get(b"k") is None
    assert db.get(b"k", ReadOptions(timestamp=4)) == b"old"  # history intact
    db.close()


def test_trim_respects_seq_snapshots(tmp_path):
    """full_history_ts_low must not drop a version a live seqno snapshot
    still reads (regression)."""
    db = DB.open(str(tmp_path / "db"), Options(comparator=U64_TS_BYTEWISE))
    db.put(b"k", b"v1", ts=1)
    snap = db.get_snapshot()
    db.put(b"k", b"v2", ts=2)
    db.increase_full_history_ts_low(10)
    db.flush()
    db.compact_range()
    assert db.get(b"k", ReadOptions(snapshot=snap)) == b"v1"
    assert db.get(b"k") == b"v2"
    snap.release()
    db.close()


def test_ts_low_persists_across_reopen(tmp_path):
    db = DB.open(str(tmp_path / "db"), Options(comparator=U64_TS_BYTEWISE))
    db.put(b"k", b"v", ts=50)
    db.increase_full_history_ts_low(40)
    db.close()
    db = DB.open(str(tmp_path / "db"), Options(comparator=U64_TS_BYTEWISE))
    assert db.options.full_history_ts_low == 40
    with pytest.raises(InvalidArgument):
        db.increase_full_history_ts_low(30)
    db.close()


def test_single_delete_and_unsupported_ops(db):
    db.put(b"k", b"v", ts=10)
    db.single_delete(b"k", ts=20)
    assert db.get(b"k") is None
    assert db.get(b"k", ReadOptions(timestamp=15)) == b"v"
    with pytest.raises(InvalidArgument):
        db.merge(b"k", b"v")
    with pytest.raises(InvalidArgument):
        db.delete_range(b"a", b"z")


def test_raw_batch_rejected_on_ts_db(db):
    """A raw (un-timestamped) key must never enter a ts DB — including via
    DB.write and transactions (regression: poisoned iteration)."""
    from toplingdb_tpu.db.write_batch import WriteBatch

    b = WriteBatch()
    b.put(b"raw", b"v")
    with pytest.raises(InvalidArgument):
        db.write(b)
    b2 = WriteBatch()
    b2.delete_range(b"a", b"z")
    with pytest.raises(InvalidArgument):
        db.write(b2)
    # iteration still healthy
    db.put(b"ok", b"v", ts=1)
    it = db.new_iterator()
    it.seek_to_first()
    assert [k for k, _ in it.entries()] == [b"ok"]


def test_reads_below_ts_low_rejected(tmp_path):
    db = DB.open(str(tmp_path / "db"), Options(comparator=U64_TS_BYTEWISE))
    db.put(b"k", b"v@10", ts=10)
    db.put(b"k", b"v@30", ts=30)
    db.increase_full_history_ts_low(20)
    for fn in (
        lambda: db.get(b"k", ReadOptions(timestamp=12)),
        lambda: db.new_iterator(ReadOptions(timestamp=12)),
        lambda: db.multi_get([b"k"], ReadOptions(timestamp=12)),
    ):
        with pytest.raises(InvalidArgument):
            fn()
    assert db.get(b"k", ReadOptions(timestamp=25)) == b"v@10"
    db.close()


def test_ts_guard_on_plain_db_iterator_and_multiget(tmp_path):
    plain = DB.open(str(tmp_path / "plain"), Options())
    with pytest.raises(InvalidArgument):
        plain.new_iterator(ReadOptions(timestamp=5))
    with pytest.raises(InvalidArgument):
        plain.multi_get([b"k"], ReadOptions(timestamp=5))
    plain.close()


def test_bottommost_drops_fully_trimmed_tombstone(tmp_path):
    """delete + whole history below ts_low at bottommost → the tombstone
    itself is reclaimed (regression: deleted keys leaking space forever)."""
    db = DB.open(str(tmp_path / "db"), Options(comparator=U64_TS_BYTEWISE))
    db.put(b"dead", b"v", ts=3)
    db.delete(b"dead", ts=5)
    db.put(b"live", b"v", ts=6)
    db.flush()
    db.increase_full_history_ts_low(100)
    db.compact_range()
    assert db.get(b"dead") is None
    assert db.get(b"live") == b"v"
    # physically gone: no version of 'dead' remains in any SST
    st = db.versions.column_families[0]
    total = sum(f.num_entries + f.num_deletions
                for _, f in st.current.all_files())
    assert total == 1  # just 'live'
    db.close()


def test_multi_get_with_ts(db):
    db.put(b"a", b"1", ts=5)
    db.put(b"b", b"2", ts=15)
    vals = db.multi_get([b"a", b"b", b"c"], ReadOptions(timestamp=10))
    assert vals == [b"1", None, None]
    assert db.multi_get([b"a", b"b"]) == [b"1", b"2"]


def test_ts_fast_lookup_matches_iterator_path(tmp_path):
    """Differential: the layered fast path and the full-iterator path agree
    on random (key, read_ts, snapshot-free) lookups across memtable + L0 +
    compacted layouts (VERDICT r2 task 9)."""
    import random

    from toplingdb_tpu.db.db import DB
    from toplingdb_tpu.options import Options, ReadOptions

    rng = random.Random(99)
    opts = Options(create_if_missing=True, comparator=U64_TS_BYTEWISE,
                   write_buffer_size=16 * 1024)
    with DB.open(str(tmp_path / "db"), opts) as db:
        keys = [b"k%04d" % i for i in range(60)]
        for ts in range(1, 40):
            k = rng.choice(keys)
            if rng.random() < 0.15:
                db.delete(k, ts=ts)
            else:
                db.put(k, b"v-%04d-%d" % (ts, rng.randrange(99)), ts=ts)
            if ts == 15:
                db.flush()
            if ts == 25:
                db.flush()
                db.compact_range()
        for k in keys:
            for read_ts in (None, 5, 14, 20, 33, 39):
                ro = ReadOptions(timestamp=read_ts)
                fast = db._ts_fast_lookup(k, ro, None)
                assert fast is not db._TS_SLOW, "fast path unexpectedly bailed"
                slow = db._ts_lookup(db.new_iterator(ro), k)
                assert fast == slow, (k, read_ts, fast, slow)


def test_ts_get_skips_iterator_build(tmp_path):
    """Perf criterion, pinned deterministically: ts point Gets resolve
    through the layered fast path — no full merging-iterator build per
    lookup (measured 0.9x of plain Get on this layout; the old path was
    the ARCHITECTURE.md-flagged per-Get iterator debt)."""
    from toplingdb_tpu.db.db import DB
    from toplingdb_tpu.options import Options

    n = 500
    with DB.open(str(tmp_path / "ts"),
                 Options(create_if_missing=True,
                         comparator=U64_TS_BYTEWISE)) as db:
        for i in range(n):
            db.put(b"key%06d" % i, b"v%06d" % i, ts=i + 1)
        db.flush()
        built = []
        orig = db.new_iterator
        db.new_iterator = lambda *a, **k: (built.append(1), orig(*a, **k))[1]
        for i in range(0, n, 5):
            assert db.get(b"key%06d" % i) == b"v%06d" % i
        assert not built, "ts-Get fell back to the full-iterator path"


def test_ts_get_resolves_blob_values(tmp_path):
    """BLOB_INDEX candidates resolve through the blob source on the fast
    path (they are values, not tombstones)."""
    from toplingdb_tpu.db.db import DB
    from toplingdb_tpu.options import Options, ReadOptions

    with DB.open(str(tmp_path / "db"),
                 Options(create_if_missing=True, comparator=U64_TS_BYTEWISE,
                         enable_blob_files=True, min_blob_size=10)) as db:
        db.put(b"k", b"x" * 100, ts=5)
        db.flush()
        assert db.get(b"k", ReadOptions(timestamp=10)) == b"x" * 100
        assert db.get_with_ts(b"k") == (b"x" * 100, 5)
