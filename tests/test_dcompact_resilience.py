"""Resilient distributed compaction: retry/backoff, per-URL circuit
breaking, graceful-degradation local pinning, job leases + orphan
sweeping, and the DCOMPACTION_* attribution of every failure."""

import json
import os
import socket
import threading
import time

import pytest

from toplingdb_tpu.compaction.dcompact_service import (
    DcompactWorkerService,
    HttpCompactionExecutorFactory,
)
from toplingdb_tpu.compaction.executor import (
    SubprocessCompactionExecutorFactory,
)
from toplingdb_tpu.compaction.resilience import (
    CircuitBreaker,
    DcompactFaultInjector,
    DcompactOptions,
    LocalPinGate,
    WorkerHealthRegistry,
    sweep_orphan_jobs,
)
from toplingdb_tpu.db.db import DB
from toplingdb_tpu.options import Options
from toplingdb_tpu.utils import statistics as st
from toplingdb_tpu.utils.statistics import Statistics


# ---------------------------------------------------------------------------
# Unit: breaker / registry / pin gate / policy
# ---------------------------------------------------------------------------


class FakeClock:
    def __init__(self):
        self.t = 1000.0

    def __call__(self):
        return self.t


def test_circuit_breaker_lifecycle():
    clk = FakeClock()
    b = CircuitBreaker(failure_threshold=3, reset_timeout=10.0, clock=clk)
    assert b.allow() and b.state == CircuitBreaker.CLOSED
    b.on_failure()
    b.on_failure()
    assert b.allow()  # still closed below the threshold
    assert b.on_failure() is True  # third consecutive: OPEN
    assert b.state == CircuitBreaker.OPEN
    assert not b.allow()
    clk.t += 9.0
    assert not b.allow()  # reset timeout not reached
    clk.t += 2.0
    assert b.allow()  # half-open probe admitted
    assert b.state == CircuitBreaker.HALF_OPEN
    assert not b.allow()  # only ONE probe at a time
    assert b.on_success() is True  # probe succeeded: CLOSED again
    assert b.state == CircuitBreaker.CLOSED and b.allow()
    # Half-open probe failure re-opens immediately.
    for _ in range(3):
        b.on_failure()
    clk.t += 11.0
    assert b.allow()
    assert b.on_failure() is True
    assert b.state == CircuitBreaker.OPEN and not b.allow()


def test_health_registry_pick_skips_open_circuits():
    clk = FakeClock()
    pol = DcompactOptions(breaker_failure_threshold=1,
                          breaker_reset_timeout=60.0)
    reg = WorkerHealthRegistry(pol, clock=clk)
    urls = ["http://a", "http://b", "http://c"]
    picks = [reg.pick(urls) for _ in range(3)]
    assert sorted(picks) == sorted(urls)  # plain round-robin when healthy
    reg.record_failure("http://b")  # threshold 1: opens immediately
    picks = {reg.pick(urls) for _ in range(6)}
    assert "http://b" not in picks and picks == {"http://a", "http://c"}
    assert reg.skipped_open > 0
    reg.record_failure("http://a")
    reg.record_failure("http://c")
    assert reg.pick(urls) is None  # every circuit open
    clk.t += 61.0
    assert reg.pick(urls) in urls  # half-open probe re-admits
    snap = reg.snapshot()
    assert set(snap) == set(urls)


def test_local_pin_gate():
    clk = FakeClock()
    pol = DcompactOptions(local_pin_failures=2, local_pin_cooldown=30.0)
    g = LocalPinGate(pol, clock=clk)
    assert not g.should_pin()
    assert g.note_job_failure() is False
    g.note_job_success()  # resets the streak
    assert g.note_job_failure() is False
    assert g.note_job_failure() is True  # second consecutive: pinned
    assert g.should_pin() and g.pin_count == 1
    clk.t += 31.0
    assert not g.should_pin()  # cooldown lapsed


def test_backoff_delay_exponential_with_jitter():
    pol = DcompactOptions(backoff_base=0.1, backoff_multiplier=2.0,
                          backoff_jitter=0.5)
    import random

    rng = random.Random(7)
    for i in (1, 2, 3):
        nominal = 0.1 * (2.0 ** (i - 1))
        for _ in range(50):
            d = pol.backoff_delay(i, rng)
            assert 0.5 * nominal <= d <= 1.5 * nominal
    nojit = DcompactOptions(backoff_base=0.1, backoff_jitter=0.0)
    assert nojit.backoff_delay(3) == pytest.approx(0.4)


def test_dcompact_options_config_roundtrip():
    from toplingdb_tpu.utils.config import (
        options_from_config, options_to_config,
    )

    opts = options_from_config({
        "dcompact": {"max_attempts": 5, "backoff_base": 0.01,
                     "lease_sec": 7.5, "breaker_failure_threshold": 2},
    })
    assert opts.dcompact.max_attempts == 5
    assert opts.dcompact.lease_sec == 7.5
    out = options_to_config(opts)
    assert out["dcompact"] == {"max_attempts": 5, "backoff_base": 0.01,
                               "lease_sec": 7.5,
                               "breaker_failure_threshold": 2}
    # Defaults serialize to nothing.
    opts2 = options_from_config({"dcompact": {}})
    assert "dcompact" not in options_to_config(opts2)


def test_fault_injector_deterministic():
    inj = DcompactFaultInjector(rate=0.5, plans=("drop",), seed=42)
    seq1 = [inj.plan(i, 0) for i in range(40)]
    inj2 = DcompactFaultInjector(rate=0.5, plans=("drop",), seed=42)
    seq2 = [inj2.plan(i, 0) for i in range(40)]
    assert seq1 == seq2 and "drop" in seq1 and None in seq1
    assert inj.injected_counts()["drop"] == sum(p == "drop" for p in seq1)


# ---------------------------------------------------------------------------
# Integration helpers
# ---------------------------------------------------------------------------


def _fill(dbp, opts, n=2400, mod=800):
    db = DB.open(dbp, opts)
    for i in range(n):
        db.put(b"key%05d" % (i % mod), b"val%07d" % i)
        if i % 300 == 299:
            db.flush()
    db.flush()
    return db


def _fast_policy(**kw):
    base = dict(max_attempts=3, backoff_base=0.005, backoff_jitter=0.1,
                attempt_timeout=120.0, breaker_failure_threshold=2,
                breaker_reset_timeout=0.2, local_pin_failures=2,
                local_pin_cooldown=0.3, lease_sec=5.0)
    base.update(kw)
    return DcompactOptions(**base)


# ---------------------------------------------------------------------------
# Integration: retry + fallback through the real scheduler (HTTP transport)
# ---------------------------------------------------------------------------


def test_http_retry_recovers_failed_attempts(tmp_path):
    """Attempt 1 of each job is dropped; the retry succeeds remotely —
    no local fallback, every failure attributed as a retry."""
    svc = DcompactWorkerService(device="cpu")
    port = svc.start()
    stats = Statistics()
    policy = _fast_policy(breaker_failure_threshold=10)
    # Every EVEN ordinal fails: each job's first attempt drops, retry runs.
    inj = DcompactFaultInjector(
        schedule={i: "drop" for i in range(0, 40, 2)})
    fac = HttpCompactionExecutorFactory(
        [f"http://127.0.0.1:{port}"], policy=policy, fault_injector=inj)
    dbp = str(tmp_path / "db")
    opts = Options(write_buffer_size=1 << 14, disable_auto_compactions=True,
                   compaction_executor_factory=fac, statistics=stats,
                   dcompact=policy)
    db = _fill(dbp, opts)
    try:
        db.compact_range()
        assert db.get(b"key00000") is not None
        assert db.get(b"key00799") == b"val%07d" % 2399
        t = stats.tickers()
        assert t.get(st.DCOMPACTION_RETRIES, 0) > 0
        assert t.get(st.DCOMPACTION_JOB_FAILURES, 0) == 0
        assert t.get(st.DCOMPACTION_FALLBACK_LOCAL, 0) == 0
        # attempts = successes (jobs) + retried failures
        n_inj = sum(inj.injected_counts().values())
        assert t[st.DCOMPACTION_ATTEMPTS] == svc.jobs_done + n_inj
        assert t[st.DCOMPACTION_RETRIES] == n_inj
        assert db._bg_error is None
    finally:
        db.close()
        svc.stop()


def test_exhausted_attempts_fall_back_local_and_pin(tmp_path):
    """Every attempt fails: the job falls back local; after N consecutive
    remote job failures the pin gate routes later jobs straight local
    (DCOMPACTION_FALLBACK_PINNED) without touching the transport."""
    stats = Statistics()
    policy = _fast_policy(max_attempts=2, local_pin_failures=1,
                          local_pin_cooldown=60.0)
    inj = DcompactFaultInjector(rate=1.0, plans=("drop",), seed=1)
    fac = SubprocessCompactionExecutorFactory(
        device="cpu", policy=policy, fault_injector=inj)
    dbp = str(tmp_path / "db")
    opts = Options(write_buffer_size=1 << 14, disable_auto_compactions=True,
                   compaction_executor_factory=fac, statistics=stats,
                   dcompact=policy)
    db = _fill(dbp, opts)
    try:
        db.compact_range()  # the L0 job exhausts its attempts -> pin
        assert db.get(b"key00799") == b"val%07d" % 2399
        t = stats.tickers()
        assert t.get(st.DCOMPACTION_JOB_FAILURES, 0) >= 1
        assert t.get(st.DCOMPACTION_FALLBACK_LOCAL, 0) >= 1
        assert t.get(st.DCOMPACTION_LOCAL_PINS, 0) == 1
        # A later job inside the cooldown goes straight local — no remote
        # attempt, no transport wait.
        attempts_before = t[st.DCOMPACTION_ATTEMPTS]
        for i in range(900):
            db.put(b"pin%05d" % (i % 300), b"pv%06d" % i)
            if i % 300 == 299:
                db.flush()
        db.compact_range()
        t = stats.tickers()
        assert t.get(st.DCOMPACTION_FALLBACK_PINNED, 0) >= 1
        assert t[st.DCOMPACTION_ATTEMPTS] == attempts_before
        assert db.get(b"pin00299") == b"pv%06d" % 899
        # The pinned jobs never spawned remote attempts.
        assert t[st.DCOMPACTION_ATTEMPTS] == \
            t[st.DCOMPACTION_RETRIES] + t[st.DCOMPACTION_JOB_FAILURES]
        assert db._bg_error is None
    finally:
        db.close()


def test_http_breaker_skips_dead_worker(tmp_path):
    """Two workers, one a black hole that accepts and never replies (a
    REAL HTTP timeout): its breaker opens after the configured consecutive
    failures and round-robin stops paying the timeout for it."""
    svc = DcompactWorkerService(device="cpu")
    port = svc.start()
    # Black-hole listener: accepts connections, never responds.
    hole = socket.socket()
    hole.bind(("127.0.0.1", 0))
    hole.listen(8)
    hole_port = hole.getsockname()[1]
    stats = Statistics()
    policy = _fast_policy(max_attempts=3, breaker_failure_threshold=1,
                          breaker_reset_timeout=300.0, attempt_timeout=0.5,
                          local_pin_failures=100)
    fac = HttpCompactionExecutorFactory(
        [f"http://127.0.0.1:{hole_port}", f"http://127.0.0.1:{port}"],
        policy=policy)
    events = []
    from toplingdb_tpu.utils.listener import EventListener

    class Watch(EventListener):
        def on_worker_health_changed(self, db, info):
            events.append((info.url, info.state))

        def on_dcompact_attempt(self, db, info):
            events.append(("attempt", info.url, info.ok))

    dbp = str(tmp_path / "db")
    opts = Options(write_buffer_size=1 << 14, disable_auto_compactions=True,
                   compaction_executor_factory=fac, statistics=stats,
                   dcompact=policy, listeners=[Watch()])
    db = _fill(dbp, opts)
    try:
        db.compact_range()
        assert db.get(b"key00799") == b"val%07d" % 2399
        t = stats.tickers()
        assert t.get(st.DCOMPACTION_BREAKER_OPEN, 0) == 1
        assert t.get(st.DCOMPACTION_FALLBACK_LOCAL, 0) == 0
        assert svc.jobs_done >= 1
        hole_url = f"http://127.0.0.1:{hole_port}"
        assert (hole_url, "open") in events
        assert any(e[0] == "attempt" and e[1] == hole_url and not e[2]
                   for e in events)
        assert fac.health.snapshot()[hole_url]["state"] == "open"
        # After the breaker opened, every further attempt went to the live
        # worker; the timeout was paid exactly once.
        failed = [e for e in events
                  if e[0] == "attempt" and e[2] is False]
        assert len(failed) == 1
        assert db._bg_error is None
    finally:
        db.close()
        svc.stop()
        hole.close()


def test_all_circuits_open_skips_to_local_without_timeout(tmp_path):
    """Every worker's breaker open -> new_executor returns None and the
    job goes local instantly (DCOMPACTION_BREAKER_SKIPPED), not after
    max_attempts * timeout."""
    stats = Statistics()
    policy = _fast_policy(breaker_failure_threshold=1,
                          breaker_reset_timeout=600.0,
                          local_pin_failures=100)
    fac = HttpCompactionExecutorFactory(
        ["http://worker-a", "http://worker-b"], policy=policy)
    fac.health.record_failure("http://worker-a")
    fac.health.record_failure("http://worker-b")
    dbp = str(tmp_path / "db")
    opts = Options(write_buffer_size=1 << 14, disable_auto_compactions=True,
                   compaction_executor_factory=fac, statistics=stats,
                   dcompact=policy)
    db = _fill(dbp, opts)
    try:
        t0 = time.monotonic()
        db.compact_range()
        elapsed = time.monotonic() - t0
        assert db.get(b"key00799") == b"val%07d" % 2399
        t = stats.tickers()
        assert t.get(st.DCOMPACTION_BREAKER_SKIPPED, 0) >= 1
        assert t.get(st.DCOMPACTION_FALLBACK_LOCAL, 0) >= 1
        assert t.get(st.DCOMPACTION_ATTEMPTS, 0) == 0
        assert elapsed < 60.0  # nothing waited on a transport timeout
    finally:
        db.close()


# ---------------------------------------------------------------------------
# Job leases + orphan sweeping
# ---------------------------------------------------------------------------


def _make_orphan(job_root, job_id=42, attempt=0, age=300.0):
    """Forge the on-disk state a kill -9'd worker leaves behind: params,
    lease, a STALE heartbeat, and a partial output SST."""
    att = os.path.join(job_root, f"job-{job_id:05d}", f"att-{attempt:02d}")
    os.makedirs(os.path.join(att, "out"), exist_ok=True)
    with open(os.path.join(att, "params.json"), "w") as f:
        json.dump({"job_id": job_id, "attempt": attempt}, f)
    with open(os.path.join(att, "lease.json"), "w") as f:
        json.dump({"job_id": job_id, "lease_sec": 5.0}, f)
    with open(os.path.join(att, "heartbeat"), "w") as f:
        f.write("9999 0.0\n")
    with open(os.path.join(att, "out", "000001.sst"), "wb") as f:
        f.write(b"\x00" * 512)  # partial output
    old = time.time() - age
    for name in ("params.json", "lease.json", "heartbeat"):
        os.utime(os.path.join(att, name), (old, old))
    os.utime(att, (old, old))
    return att


def test_sweep_orphan_jobs_unit(tmp_path):
    root = str(tmp_path / "dcompact")
    dead = _make_orphan(root, job_id=1, age=300.0)
    live = _make_orphan(root, job_id=2, age=0.0)  # fresh heartbeat: live
    stats = Statistics()
    swept = sweep_orphan_jobs(root, lease_sec=30.0, statistics=stats)
    assert dead in swept and not os.path.exists(dead)
    assert os.path.exists(live)
    assert not os.path.exists(os.path.dirname(dead))  # skeleton removed
    assert stats.get_ticker_count(st.DCOMPACTION_ORPHANS_SWEPT) == 1
    # Idempotent.
    assert sweep_orphan_jobs(root, lease_sec=30.0) == []


def test_orphaned_job_swept_on_open_and_job_reruns(tmp_path):
    """Acceptance: an orphaned job dir with an expired lease left by a
    kill -9'd worker is detected and swept on DB open, and the compaction
    whose job died re-runs successfully (its inputs are still live in the
    version, so the picker re-picks it)."""
    dbp = str(tmp_path / "db")
    stats = Statistics()
    policy = _fast_policy()
    opts = Options(write_buffer_size=1 << 14, disable_auto_compactions=True,
                   level0_file_num_compaction_trigger=2)
    db = _fill(dbp, opts, n=1800, mod=600)
    v = db.versions.cf_current(0)
    assert len(v.files[0]) >= 2  # a compaction is due the moment auto is on
    db.close()
    # The worker that was running that compaction died mid-job. The forged
    # id must not collide with the process-wide job counter: the reopened
    # DB's background compaction creates fresh job-NNNNN dirs right after
    # the sweep.
    orphan = _make_orphan(os.path.join(dbp, "dcompact"), job_id=99942,
                          age=600.0)
    svc = DcompactWorkerService(device="cpu")
    port = svc.start()
    fac = HttpCompactionExecutorFactory(
        [f"http://127.0.0.1:{port}"], policy=policy)
    opts2 = Options(write_buffer_size=1 << 14,
                    level0_file_num_compaction_trigger=2,
                    compaction_executor_factory=fac, statistics=stats,
                    dcompact=policy)
    db = DB.open(dbp, opts2)
    try:
        assert not os.path.exists(orphan)
        assert stats.get_ticker_count(st.DCOMPACTION_ORPHANS_SWEPT) == 1
        db.wait_for_compactions()
        assert svc.jobs_done >= 1  # the job re-ran through the worker
        assert db.get(b"key00599") == b"val%07d" % 1799
        v = db.versions.cf_current(0)
        assert len(v.files[0]) < 2
        assert db._bg_error is None
    finally:
        db.close()
        svc.stop()


def test_worker_heartbeats_while_running(tmp_path):
    """The worker process heartbeats its job dir at ~lease/3 so the lease
    stays fresh for as long as the job actually runs."""
    from toplingdb_tpu.compaction.resilience import HeartbeatWriter

    hb = HeartbeatWriter(str(tmp_path), lease_sec=0.9)
    hb.start()
    p = os.path.join(str(tmp_path), "heartbeat")
    assert os.path.exists(p)
    m0 = os.path.getmtime(p)
    deadline = time.time() + 3.0
    while os.path.getmtime(p) == m0 and time.time() < deadline:
        time.sleep(0.05)
    assert os.path.getmtime(p) > m0  # it beats
    hb.stop()
    m1 = os.path.getmtime(p)
    time.sleep(0.7)
    assert os.path.getmtime(p) == m1  # and stops cleanly
