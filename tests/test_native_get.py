"""Native point-read engine parity: tpulsm_db_get must agree byte-for-byte
with the Python GetImpl chain (reference db_impl.cc:2079) across deletes,
overwrites, snapshots, merges, range tombstones, and multi-level layouts.

The native path silently FALLS BACK for anything it can't decide; these
tests therefore (a) check result parity native-vs-python on mixed
workloads and (b) assert the fast path actually engages on the plain
workload so parity isn't vacuously comparing python to python."""

import random

import pytest

from toplingdb_tpu.db.db import DB, ReadOptions
from toplingdb_tpu.options import Options


def _fill_mixed(db, n=20000, seed=11):
    rng = random.Random(seed)
    model = {}
    for i in range(n):
        k = b"k%06d" % rng.randrange(n // 3)
        r = rng.random()
        if r < 0.12:
            db.delete(k)
            model[k] = None
        else:
            v = b"val-%d" % i
            db.put(k, v)
            model[k] = v
        if i % 5000 == 4999:
            db.flush()
    return model


def _python_get(db, key, opts=ReadOptions()):
    """Force the Python chain by bypassing the native fast path."""
    lib = db._nget_lib
    db._nget_lib = None
    try:
        return db.get(key, opts)
    finally:
        db._nget_lib = lib


def _native_ready(db) -> bool:
    db.get(b"\x00probe")  # initializes the lazy _nget_lib
    return getattr(db, "_nget_lib", None) not in (False, None)


def test_native_get_parity_mixed(tmp_path):
    with DB.open(str(tmp_path / "db"),
                 Options(create_if_missing=True)) as db:
        model = _fill_mixed(db)
        db.flush()
        db.wait_for_compactions()
        if not _native_ready(db):
            pytest.skip("native engine unavailable")
        for k, want in list(model.items())[:4000]:
            got = db.get(k)
            assert got == want, k
            assert _python_get(db, k) == got, k
        # absent keys
        for i in range(500):
            k = b"zz%06d" % i
            assert db.get(k) is None
            assert _python_get(db, k) is None


def test_native_get_engages(tmp_path):
    """The fast path must actually run on the plain workload (guard
    against a silent always-fallback regression)."""
    with DB.open(str(tmp_path / "db"),
                 Options(create_if_missing=True)) as db:
        for i in range(3000):
            db.put(b"k%05d" % i, b"v%d" % i)
        db.flush()
        if not _native_ready(db):
            pytest.skip("native engine unavailable")
        assert db.get(b"k00042") == b"v42"
        states = getattr(db._nget_tl, "states", None)
        assert states, "native get state was never built"
        cc = states[0]
        out = cc.out
        # A successful native SST probe recorded a source level >= 1.
        assert out[1] >= 1


def test_native_get_snapshot_visibility(tmp_path):
    with DB.open(str(tmp_path / "db"),
                 Options(create_if_missing=True)) as db:
        db.put(b"a", b"v1")
        snap = db.get_snapshot()
        db.put(b"a", b"v2")
        db.delete(b"b")
        db.flush()
        opts = ReadOptions(snapshot=snap)
        assert db.get(b"a", opts) == b"v1"
        assert db.get(b"a") == b"v2"
        db.release_snapshot(snap)


def test_native_get_range_tombstone_fallback(tmp_path):
    """Range tombstones route through the Python path (memtable check +
    eligible=0 table handles) — results must stay correct."""
    with DB.open(str(tmp_path / "db"),
                 Options(create_if_missing=True)) as db:
        for i in range(1000):
            db.put(b"k%04d" % i, b"v%d" % i)
        db.flush()
        db.delete_range(b"k0100", b"k0200")
        assert db.get(b"k0150") is None
        assert db.get(b"k0050") == b"v50"
        db.flush()
        assert db.get(b"k0150") is None
        assert db.get(b"k0099") == b"v99"
        assert db.get(b"k0200") == b"v200"


def test_native_get_merge_fallback(tmp_path):
    from toplingdb_tpu.utils.merge_operator import UInt64AddOperator

    with DB.open(str(tmp_path / "db"),
                 Options(create_if_missing=True,
                         merge_operator=UInt64AddOperator())) as db:
        db.merge(b"ctr", (5).to_bytes(8, "little"))
        db.flush()
        db.merge(b"ctr", (7).to_bytes(8, "little"))
        db.put(b"plain", b"x")
        db.flush()
        assert int.from_bytes(db.get(b"ctr"), "little") == 12
        assert db.get(b"plain") == b"x"


def test_native_multiget_parity(tmp_path):
    with DB.open(str(tmp_path / "db"),
                 Options(create_if_missing=True)) as db:
        model = _fill_mixed(db, n=10000, seed=23)
        db.flush()
        db.wait_for_compactions()
        keys = list(model.keys())[:3000] + [b"absent%d" % i
                                            for i in range(100)]
        got = db.multi_get(keys)
        for k, v in zip(keys, got):
            assert v == model.get(k), k
        singles = [db.get(k) for k in keys]
        assert singles == got
