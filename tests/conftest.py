"""Test configuration.

JAX tests run on a virtual 8-device CPU mesh (multi-chip sharding is validated
without TPU hardware, as the reference's distributed paths are tested
in-process — SURVEY.md §4). These env vars must be set before jax imports.
"""

import os
import sys

# Force the CPU backend: the axon (TPU) sitecustomize bootstrap sets
# JAX_PLATFORMS=axon before pytest starts, so setdefault would be a no-op —
# and it may ALSO have imported jax already, in which case the env var was
# captured at import time and only jax.config can redirect the platform.
os.environ["JAX_PLATFORMS"] = "cpu"
os.environ["PALLAS_AXON_POOL_IPS"] = ""
flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (
        flags + " --xla_force_host_platform_device_count=8"
    ).strip()
if "jax" in sys.modules:
    import jax

    try:
        jax.config.update("jax_platforms", "cpu")
    except Exception:
        pass

import pytest  # noqa: E402


def pytest_configure(config):
    config.addinivalue_line(
        "markers",
        "slow: long-running tests excluded from tier-1 (-m 'not slow')")


@pytest.fixture
def no_thread_leaks():
    """Opt-in guard: the test must not leave any ThreadRegistry-tracked
    background thread behind (concurrency plane, ISSUE 13)."""
    from toplingdb_tpu.utils import concurrency as ccy

    before = {id(t) for t in ccy.registry.live()}
    yield
    leaked = [t.name for t in ccy.registry.live() if id(t) not in before]
    assert not leaked, f"test leaked registered threads: {leaked}"


@pytest.fixture
def mem_env():
    from toplingdb_tpu.env import MemEnv

    return MemEnv()


@pytest.fixture
def tmp_db_path(tmp_path):
    return str(tmp_path / "db")
