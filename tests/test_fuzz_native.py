"""Budgeted runs of the greybox fuzz harness (tools/fuzz_native.py — the
reference's fuzz/ targets role) + regression for its first finding."""

import os

import pytest

from toplingdb_tpu import native
from toplingdb_tpu.tools import fuzz_native as fz

pytestmark = pytest.mark.skipif(native.lib() is None,
                                reason="native library unavailable")


@pytest.mark.parametrize("target,runs", [
    ("wb", 400), ("block", 400), ("scan", 200), ("manifest", 25),
    ("abi", 800),
])
def test_fuzz_target_budgeted(target, runs, tmp_path):
    import random

    rng = random.Random(99)
    corpus = fz.Corpus(str(tmp_path / target))
    findings = fz.TARGETS[target](rng, runs, corpus)
    assert findings == 0
    # The novelty search must discover more than one behavior class.
    assert len(corpus.signatures) >= 2
    # Corpus persistence: interesting inputs landed on disk for reuse.
    assert os.listdir(str(tmp_path / target))


def test_shapes_come_from_the_parsed_contract():
    """The abi target's argument lists are generated from the SAME three
    sources the ABI checker cross-validates; handle-taking symbols (`:!`
    specs) are correctly refused rather than minted from garbage."""
    import random

    sigs, bindings, rows = fz.load_abi_contract()
    rng = random.Random(7)
    for sym in fz.ABI_FUZZ_SYMS:
        shaped = fz.shapes_from_contract(rng, sym, sigs, bindings, rows,
                                         b"\x00" * 32)
        assert shaped is not None, sym
        args, _keep = shaped
        assert len(args) == len(sigs[sym][1])  # one value per C parameter
    # Opaque-handle symbols are not fuzzable from bytes.
    assert fz.shapes_from_contract(rng, "tpulsm_db_get", sigs, bindings,
                                   rows, b"") is None


def test_manifest_garbage_head_fails_open(tmp_path):
    """fuzz_native's first finding: an all-garbage MANIFEST must fail the
    open with Corruption — NOT 'recover' an empty DB (silent data loss).
    The log reader's torn-tail tolerance only applies after a good
    snapshot record (reference VersionSet::Recover field checks)."""
    from toplingdb_tpu.db.db import DB
    from toplingdb_tpu.options import Options
    from toplingdb_tpu.utils.status import Corruption

    d = str(tmp_path / "db")
    db = DB.open(d, Options(create_if_missing=True))
    for i in range(100):
        db.put(b"k%03d" % i, b"v")
    db.flush()
    db.close()
    cur = open(os.path.join(d, "CURRENT")).read().strip()
    mpath = os.path.join(d, cur)
    raw = open(mpath, "rb").read()
    open(mpath, "wb").write(b"\xff" * len(raw))
    with pytest.raises(Corruption):
        DB.open(d, Options())
