"""ForwardIterator (tailing) tests — reference db/forward_iterator.cc via
ReadOptions.tailing."""

import pytest

from toplingdb_tpu.db.db import DB
from toplingdb_tpu.db.forward_iterator import ForwardIterator
from toplingdb_tpu.options import Options, ReadOptions
from toplingdb_tpu.utils.status import NotSupported


@pytest.fixture
def db(tmp_path):
    d = DB.open(str(tmp_path / "db"), Options())
    yield d
    d.close()


def test_tailing_sees_new_writes(db):
    db.put(b"a", b"1")
    db.put(b"b", b"2")
    it = db.new_iterator(ReadOptions(tailing=True))
    assert isinstance(it, ForwardIterator)
    it.seek_to_first()
    assert it.valid() and it.key() == b"a"
    it.next()
    assert it.key() == b"b"
    it.next()
    assert not it.valid()  # exhausted
    # new writes arrive AFTER exhaustion
    db.put(b"c", b"3")
    db.put(b"d", b"4")
    it.next()  # catch-up resumes after b
    assert it.valid() and it.key() == b"c"
    it.next()
    assert it.key() == b"d"
    it.next()
    assert not it.valid()
    # still nothing new: next() again stays invalid (tail loop contract)
    it.next()
    assert not it.valid()


def test_tailing_across_flush(db):
    db.put(b"k1", b"v1")
    it = db.new_iterator(ReadOptions(tailing=True))
    it.seek_to_first()
    assert it.key() == b"k1"
    it.next()
    assert not it.valid()
    db.flush()               # k1 moves memtable → SST
    db.put(b"k2", b"v2")     # new write in fresh memtable
    db.flush()
    db.put(b"k3", b"v3")
    it.next()
    got = [(it.key(), it.value())]
    it.next()
    got.append((it.key(), it.value()))
    assert got == [(b"k2", b"v2"), (b"k3", b"v3")]


def test_tailing_no_duplicate_on_overwrite(db):
    db.put(b"a", b"1")
    it = db.new_iterator(ReadOptions(tailing=True))
    it.seek_to_first()
    it.next()
    assert not it.valid()
    db.put(b"a", b"updated")  # overwrite BEHIND the tail position
    db.put(b"z", b"new")
    it.next()
    # only the new key shows; the overwrite of an already-returned key is
    # behind the cursor (forward-only contract)
    assert it.valid() and it.key() == b"z"


def test_tailing_seek_and_restrictions(db):
    for i in range(10):
        db.put(b"k%02d" % i, b"v")
    it = db.new_iterator(ReadOptions(tailing=True))
    it.seek(b"k05")
    assert it.key() == b"k05"
    with pytest.raises(NotSupported):
        it.prev()
    with pytest.raises(NotSupported):
        it.seek_to_last()
    snap = db.get_snapshot()
    with pytest.raises(NotSupported):
        db.new_iterator(ReadOptions(tailing=True, snapshot=snap))
    db.release_snapshot(snap)


def test_tailing_seek_past_end_then_catch_up(db):
    """A seek that lands at end-of-data must resume AT the target — never
    restart from the first key."""
    db.put(b"a", b"1")
    it = db.new_iterator(ReadOptions(tailing=True))
    it.seek(b"m")          # past everything
    assert not it.valid()
    db.put(b"b", b"2")     # before the seek target: must NOT surface
    db.put(b"n", b"3")     # at/after the target
    it.next()
    assert it.valid() and it.key() == b"n"
    # empty-DB tail loop from seek_to_first
    it2 = db.new_iterator(ReadOptions(tailing=True))
    it2.seek_to_first()
    # (db nonempty here, so position at first)
    assert it2.valid()


def test_tailing_empty_db_tail_loop(tmp_path):
    d = DB.open(str(tmp_path / "empty"), Options())
    it = d.new_iterator(ReadOptions(tailing=True))
    it.seek_to_first()
    assert not it.valid()
    d.put(b"x", b"1")
    it.next()
    assert it.valid() and it.key() == b"x"
    d.close()


def test_tailing_respects_deletes(db):
    db.put(b"a", b"1")
    it = db.new_iterator(ReadOptions(tailing=True))
    it.seek_to_first()
    it.next()
    assert not it.valid()
    db.put(b"b", b"2")
    db.delete(b"b")
    db.put(b"c", b"3")
    it.next()
    assert it.valid() and it.key() == b"c"  # deleted b never surfaces
