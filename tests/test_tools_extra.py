"""Tool coverage: microbench primitives, extra db_bench workloads, and the
SstFileWriter fuzz (reference fuzz/sst_file_writer_fuzzer.cc: random KVs →
writer → reader must round-trip and survive truncation checks)."""

import json
import random
import subprocess
import sys

import pytest


def test_microbench_runs():
    out = subprocess.run(
        [sys.executable, "-m", "toplingdb_tpu.tools.microbench", "--n=2000"],
        capture_output=True, timeout=300, cwd="/root/repo",
    )
    assert out.returncode == 0, out.stderr.decode()
    lines = [json.loads(x) for x in out.stdout.decode().splitlines() if x]
    names = {r["bench"] for r in lines}
    assert {"crc32c_1MiB", "memtable_insert", "table_build",
            "table_scan"} <= names
    assert all(r["items_per_s"] > 0 for r in lines)


def test_db_bench_extra_workloads(tmp_path):
    from toplingdb_tpu.tools import db_bench

    rc = db_bench.main([
        f"--db={tmp_path}/b",
        "--benchmarks=fillseq,seekrandom,mergerandom,fillrandombatch,stats",
        "--num=2000",
    ])
    assert rc == 0


@pytest.mark.parametrize("seed", [3, 9])
def test_sst_file_writer_fuzz(tmp_path, seed):
    from toplingdb_tpu.utilities.sst_file_writer import (
        SstFileReader, SstFileWriter,
    )
    from toplingdb_tpu.utils.status import Corruption

    rng = random.Random(seed)
    keys = sorted({bytes(rng.randrange(32, 127) for _ in
                         range(rng.randrange(1, 40)))
                   for _ in range(rng.randrange(10, 400))})
    vals = {k: bytes(rng.randrange(256) for _ in range(rng.randrange(0, 200)))
            for k in keys}
    path = str(tmp_path / f"f{seed}.sst")
    w = SstFileWriter()
    w.open(path)
    for k in keys:
        w.put(k, vals[k])
    w.finish()
    r = SstFileReader(path)
    assert r.properties.num_entries == len(keys)
    got = {}
    from toplingdb_tpu.db import dbformat
    from toplingdb_tpu.db.dbformat import InternalKeyComparator
    from toplingdb_tpu.env import PosixEnv
    from toplingdb_tpu.table.factory import open_table

    tr = open_table(PosixEnv().new_random_access_file(path),
                    InternalKeyComparator())
    it = tr.new_iterator()
    it.seek_to_first()
    for ik, v in it.entries():
        got[dbformat.extract_user_key(ik)] = v
    assert got == vals
    # Corrupt a byte mid-file: reads must fail loudly, not return garbage.
    data = bytearray(open(path, "rb").read())
    data[len(data) // 2] ^= 0x5A
    open(path, "wb").write(bytes(data))
    with pytest.raises(Corruption):
        tr2 = open_table(PosixEnv().new_random_access_file(path),
                         InternalKeyComparator())
        it2 = tr2.new_iterator()
        it2.seek_to_first()
        for _ in it2.entries():
            pass


def test_db_bench_full_workload_matrix(tmp_path, capsys):
    """Every dispatchable workload runs green (the reference's ~40-name
    dispatch table, tools/db_bench_tool.cc:3784-3893)."""
    import re

    from toplingdb_tpu.tools import db_bench

    names = ("fillseq,readseq,readreverse,readrandom,readmissing,readhot,"
             "seekrandom,fillrandom,overwrite,updaterandom,appendrandom,"
             "readrandomwriterandom,mergerandom,readwhilemerging,"
             "readwhilewriting,seekrandomwhilewriting,multireadrandom,"
             "fillsync,fill100K,fillseekseq,deleterandom,deleteseq,flush,"
             "compact,compactall,waitforcompaction,verifychecksum,crc32c,"
             "xxhash,stats,levelstats,sstables,memstats,randomtransaction")
    rc = db_bench.main([
        "--num=400", f"--db={tmp_path / 'bench'}",
        f"--benchmarks={names}",
    ])
    assert rc == 0
    out = capsys.readouterr().out
    for name in names.split(","):
        assert re.search(rf"^{name} ", out, re.M), \
            f"workload {name} produced no report line"
    assert "unknown benchmark" not in out
