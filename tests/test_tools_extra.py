"""Tool coverage: microbench primitives, extra db_bench workloads, and the
SstFileWriter fuzz (reference fuzz/sst_file_writer_fuzzer.cc: random KVs →
writer → reader must round-trip and survive truncation checks)."""

import json
import random
import subprocess
import sys

import pytest


def test_microbench_runs():
    out = subprocess.run(
        [sys.executable, "-m", "toplingdb_tpu.tools.microbench", "--n=2000"],
        capture_output=True, timeout=300, cwd="/root/repo",
    )
    assert out.returncode == 0, out.stderr.decode()
    lines = [json.loads(x) for x in out.stdout.decode().splitlines() if x]
    names = {r["bench"] for r in lines}
    assert {"crc32c_1MiB", "memtable_insert", "table_build",
            "table_scan"} <= names
    assert all(r["items_per_s"] > 0 for r in lines
               if "items_per_s" in r)  # *_stats rows carry counters instead
    assert any(r["bench"] == "persistent_cache_tier_stats"
               and r["hit_rate"] > 0 for r in lines)


def test_db_bench_extra_workloads(tmp_path):
    from toplingdb_tpu.tools import db_bench

    rc = db_bench.main([
        f"--db={tmp_path}/b",
        "--benchmarks=fillseq,seekrandom,mergerandom,fillrandombatch,stats",
        "--num=2000",
    ])
    assert rc == 0


@pytest.mark.parametrize("seed", [3, 9])
def test_sst_file_writer_fuzz(tmp_path, seed):
    from toplingdb_tpu.utilities.sst_file_writer import (
        SstFileReader, SstFileWriter,
    )
    from toplingdb_tpu.utils.status import Corruption

    rng = random.Random(seed)
    keys = sorted({bytes(rng.randrange(32, 127) for _ in
                         range(rng.randrange(1, 40)))
                   for _ in range(rng.randrange(10, 400))})
    vals = {k: bytes(rng.randrange(256) for _ in range(rng.randrange(0, 200)))
            for k in keys}
    path = str(tmp_path / f"f{seed}.sst")
    w = SstFileWriter()
    w.open(path)
    for k in keys:
        w.put(k, vals[k])
    w.finish()
    r = SstFileReader(path)
    assert r.properties.num_entries == len(keys)
    got = {}
    from toplingdb_tpu.db import dbformat
    from toplingdb_tpu.db.dbformat import InternalKeyComparator
    from toplingdb_tpu.env import PosixEnv
    from toplingdb_tpu.table.factory import open_table

    tr = open_table(PosixEnv().new_random_access_file(path),
                    InternalKeyComparator())
    it = tr.new_iterator()
    it.seek_to_first()
    for ik, v in it.entries():
        got[dbformat.extract_user_key(ik)] = v
    assert got == vals
    # Corrupt a byte mid-file: reads must fail loudly, not return garbage.
    data = bytearray(open(path, "rb").read())
    data[len(data) // 2] ^= 0x5A
    open(path, "wb").write(bytes(data))
    with pytest.raises(Corruption):
        tr2 = open_table(PosixEnv().new_random_access_file(path),
                         InternalKeyComparator())
        it2 = tr2.new_iterator()
        it2.seek_to_first()
        for _ in it2.entries():
            pass


def test_db_bench_full_workload_matrix(tmp_path, capsys):
    """Every dispatchable workload runs green (the reference's ~40-name
    dispatch table, tools/db_bench_tool.cc:3784-3893)."""
    import re

    from toplingdb_tpu.tools import db_bench

    names = ("fillseq,readseq,readreverse,readrandom,readmissing,readhot,"
             "seekrandom,fillrandom,overwrite,updaterandom,appendrandom,"
             "readrandomwriterandom,mergerandom,readwhilemerging,"
             "readwhilewriting,seekrandomwhilewriting,multireadrandom,"
             "fillsync,fill100K,fillseekseq,deleterandom,deleteseq,flush,"
             "compact,compactall,waitforcompaction,verifychecksum,crc32c,"
             "xxhash,stats,levelstats,sstables,memstats,randomtransaction")
    rc = db_bench.main([
        "--num=400", f"--db={tmp_path / 'bench'}",
        f"--benchmarks={names}",
    ])
    assert rc == 0
    out = capsys.readouterr().out
    for name in names.split(","):
        assert re.search(rf"^{name} ", out, re.M), \
            f"workload {name} produced no report line"
    assert "unknown benchmark" not in out


def test_db_start_trace_records_everything(tmp_path):
    """DB::StartTrace role: every Get/Write/MultiGet/Iterator-seek issued
    through the DB is captured (not just calls routed through the wrapper
    Tracer), and the Replayer reproduces the workload's end state."""
    from toplingdb_tpu.db.db import DB
    from toplingdb_tpu.options import Options
    from toplingdb_tpu.utils.trace import Replayer, read_trace

    src = str(tmp_path / "src")
    trace = str(tmp_path / "ops.trace")
    with DB.open(src, Options(create_if_missing=True)) as db:
        db.start_trace(trace)
        for i in range(200):
            db.put(b"k%04d" % i, b"v%d" % i)
        db.delete(b"k0007")
        db.get(b"k0005")
        db.multi_get([b"k0001", b"k0002"])
        it = db.new_iterator()
        it.seek(b"k0100")
        assert it.valid() and it.key() == b"k0100"
        db.end_trace()
        # post-end ops must NOT be recorded
        db.put(b"untraced", b"x")

    from toplingdb_tpu.env import default_env

    ops = list(read_trace(default_env(), trace))
    kinds = [op for op, _, _ in ops]
    from toplingdb_tpu.utils import trace as T

    assert kinds.count(T.OP_WRITE_BATCH) == 201  # 200 puts + 1 delete
    assert T.OP_GET in kinds and T.OP_MULTIGET in kinds
    assert T.OP_ITER_SEEK in kinds
    assert not any(s and s[0] == b"untraced" for _, _, s in ops)

    dst = str(tmp_path / "dst")
    with DB.open(dst, Options(create_if_missing=True)) as db2:
        n = Replayer(db2, trace).replay()
        assert n == len(ops)
        assert db2.get(b"k0005") == b"v5"
        assert db2.get(b"k0007") is None
        assert db2.get(b"untraced") is None


def test_trace_sampling_and_size_cap(tmp_path):
    from toplingdb_tpu.db.db import DB
    from toplingdb_tpu.env import default_env
    from toplingdb_tpu.options import Options
    from toplingdb_tpu.utils.trace import TraceOptions, read_trace

    trace = str(tmp_path / "s.trace")
    with DB.open(str(tmp_path / "db"),
                 Options(create_if_missing=True)) as db:
        db.start_trace(trace, TraceOptions(sampling_frequency=10))
        for i in range(500):
            db.get(b"k%d" % i)
        db.end_trace()
    ops = list(read_trace(default_env(), trace))
    assert len(ops) == 50  # exactly 1-in-10

    cap = str(tmp_path / "cap.trace")
    with DB.open(str(tmp_path / "db2"),
                 Options(create_if_missing=True)) as db:
        db.start_trace(cap, TraceOptions(max_trace_file_size=2000))
        for i in range(5000):
            db.get(b"key%06d" % i)
        assert db._op_tracer.stopped
        db.end_trace()
    sz = len(open(cap, "rb").read())
    assert sz <= 4096  # stopped near the cap, not 5000 records


def test_replay_timing_faithful_speedup(tmp_path):
    import time as _time

    from toplingdb_tpu.db.db import DB
    from toplingdb_tpu.options import Options
    from toplingdb_tpu.utils.trace import Replayer

    trace = str(tmp_path / "t.trace")
    with DB.open(str(tmp_path / "db"),
                 Options(create_if_missing=True)) as db:
        db.start_trace(trace)
        db.put(b"a", b"1")
        _time.sleep(0.3)
        db.put(b"b", b"2")
        db.end_trace()
    with DB.open(str(tmp_path / "dst"),
                 Options(create_if_missing=True)) as db2:
        t0 = _time.time()
        Replayer(db2, trace).replay(fast_forward=False, speedup=10.0)
        dt = _time.time() - t0
        assert dt < 0.25, dt  # 0.3s gap compressed ~10x
        t0 = _time.time()
        Replayer(db2, trace).replay(fast_forward=False, speedup=1.0)
        assert _time.time() - t0 >= 0.25  # faithful replay keeps the gap


def test_ldb_backup_restore_idump_compact(tmp_path):
    """ldb gains compact / idump / backup / offline restore (reference
    ldb command surfaces)."""
    import subprocess
    import sys

    base = str(tmp_path)
    d = base + "/db"

    def run(*a):
        return subprocess.run(
            [sys.executable, "-m", "toplingdb_tpu.tools.ldb", *a],
            capture_output=True, text=True, timeout=120)

    assert run("--db", d, "put", "alpha", "one").returncode == 0
    assert run("--db", d, "put", "beta", "two").returncode == 0
    assert "compaction done" in run("--db", d, "compact").stdout
    out = run("--db", d, "idump", "--limit", "10").stdout
    assert "alpha" in out and "VALUE" in out
    assert "backup 1 created" in run("--db", d, "backup",
                                     base + "/bk").stdout
    assert run("--db", base + "/restored", "restore", base + "/bk",
               "1").returncode == 0
    assert run("--db", base + "/restored",
               "get", "alpha").stdout.strip() == "one"
