"""ZipTable (searchable-compression L2+ format): round-trip, seek
semantics, DB integration via bottommost_format, recovery."""

import random

import pytest

from toplingdb_tpu.db.dbformat import (
    InternalKeyComparator,
    ValueType,
    make_internal_key,
)
from toplingdb_tpu.table.builder import TableOptions
from toplingdb_tpu.table.factory import new_table_builder, open_table
from toplingdb_tpu.table import format as fmt

ICMP = InternalKeyComparator()


def _build(env, path, entries, topts):
    w = env.new_writable_file(path)
    b = new_table_builder(w, ICMP, topts)
    for k, v in entries:
        b.add(k, v)
    props = b.finish()
    w.close()
    return props


def _entries(rng, n, vlen_lo=4, vlen_hi=60):
    out = {}
    seq = 1
    for _ in range(n):
        k = b"user%07d" % rng.randrange(n * 3)
        out[k] = (make_internal_key(k, seq, ValueType.VALUE),
                  bytes(rng.randrange(97, 123)
                        for _ in range(rng.randrange(vlen_lo, vlen_hi))))
        seq += 1
    return [out[k] for k in sorted(out)]


@pytest.mark.parametrize("compression", [fmt.NO_COMPRESSION, fmt.ZSTD_COMPRESSION])
@pytest.mark.parametrize("n", [1, 15, 16, 17, 400])
def test_zip_round_trip(tmp_path, n, compression):
    from toplingdb_tpu.env import default_env
    from toplingdb_tpu.table.zip_table import ZipTableReader

    env = default_env()
    rng = random.Random(n + compression)
    entries = _entries(rng, n)
    topts = TableOptions(format="zip", compression=compression,
                        filter_policy=None)
    path = str(tmp_path / "t.sst")
    props = _build(env, path, entries, topts)
    assert props.num_entries == len(entries)
    r = open_table(env.new_random_access_file(path), ICMP, topts)
    assert isinstance(r, ZipTableReader)
    it = r.new_iterator()
    it.seek_to_first()
    got = list(it.entries())
    assert got == entries
    # point seeks land on the exact entry
    for k, v in entries[:: max(1, len(entries) // 37)]:
        it.seek(k)
        assert it.valid() and it.key() == k and it.value() == v
    # seek between keys lands on the successor
    for i in range(0, len(entries) - 1, max(1, len(entries) // 11)):
        probe = entries[i][0][:-8] + b"\x00\xff"
        it.seek(make_internal_key(probe, 1 << 40, ValueType.MAX))
        assert it.valid() and it.key() == entries[i + 1][0]
    # reverse iteration
    it.seek_to_last()
    rev = []
    while it.valid():
        rev.append((it.key(), it.value()))
        it.prev()
    assert rev == entries[::-1]


def test_zip_dict_compression_and_big_values(tmp_path):
    from toplingdb_tpu.env import default_env
    from toplingdb_tpu.table.builder import CompressionOptions
    from toplingdb_tpu.utils import codecs

    if not codecs.available("zstd"):
        pytest.skip("libzstd unavailable")
    env = default_env()
    rng = random.Random(7)
    entries = []
    for i in range(3000):
        k = make_internal_key(b"k%07d" % i, i + 1, ValueType.VALUE)
        v = (b"prefix-common-" * 3) + (b"%d" % (i % 50)) * rng.randrange(1, 9)
        entries.append((k, v))
    # one giant value forces the 32-bit length directory
    entries[1234] = (entries[1234][0], b"Z" * 70000)
    topts = TableOptions(format="zip", compression=fmt.ZSTD_COMPRESSION,
                        filter_policy=None,
                        compression_opts=CompressionOptions(max_dict_bytes=4096))
    path = str(tmp_path / "d.sst")
    props = _build(env, path, entries, topts)
    assert props.compression_name == "zip+zstd"
    r = open_table(env.new_random_access_file(path), ICMP, topts)
    assert r.value_at(1234) == b"Z" * 70000
    it = r.new_iterator()
    it.seek_to_first()
    assert list(it.entries()) == entries
    # compressed smaller than raw
    raw = sum(len(k) + len(v) for k, v in entries)
    import os
    assert os.path.getsize(path) < raw


def test_zip_bottommost_format_in_db(tmp_path):
    """Fill + flush + compact: bottommost outputs are zip tables; reads,
    iteration and recovery all work over the mixed-format DB."""
    import os

    from toplingdb_tpu.db.db import DB
    from toplingdb_tpu.options import Options

    opts = Options(write_buffer_size=1 << 20, bottommost_format="zip",
                   disable_auto_compactions=True)
    d = str(tmp_path / "db")
    with DB.open(d, opts) as db:
        for i in range(5000):
            db.put(b"key%06d" % (i % 2000), b"val%07d" % i)
        db.delete_range(b"key000100", b"key000200")
        db.flush()
        db.compact_range()
        assert db.get(b"key000150") is None
        assert db.get(b"key001999") == b"val%07d" % 3999
        it = db.new_iterator()
        it.seek_to_first()
        count = sum(1 for _ in it.entries())
        assert count == 2000 - 100
    with DB.open(d, opts) as db2:
        assert db2.get(b"key000500") == b"val%07d" % 4500
        assert db2.get(b"key000150") is None
        # the bottommost file really is a zip table
        from toplingdb_tpu.table.zip_table import ZipTableReader

        v = db2.versions.current
        files = [f for lvl, f in v.all_files() if lvl > 0]
        assert files, "no bottommost files"
        for f in files:
            r = db2.table_cache.get_reader(f.number)
            assert isinstance(r, ZipTableReader)


def test_zip_tombstone_only_file(tmp_path):
    from toplingdb_tpu.env import default_env
    from toplingdb_tpu.table.zip_table import ZipTableReader

    env = default_env()
    topts = TableOptions(format="zip", filter_policy=None)
    path = str(tmp_path / "t.sst")
    w = env.new_writable_file(path)
    b = new_table_builder(w, ICMP, topts)
    b.add_tombstone(make_internal_key(b"a", 9, ValueType.RANGE_DELETION), b"m")
    b.finish()
    w.close()
    r = open_table(env.new_random_access_file(path), ICMP, topts)
    assert isinstance(r, ZipTableReader)
    assert len(r.range_del_entries()) == 1
    it = r.new_iterator()
    it.seek_to_first()
    assert not it.valid()


def test_zip_long_keys_meta16(tmp_path):
    """Keys past 255 bytes switch the front-coding meta to u16 pairs — no
    compaction-killing cap."""
    from toplingdb_tpu.env import default_env
    from toplingdb_tpu.table.zip_table import ZipTableReader

    env = default_env()
    rng = random.Random(42)
    entries = []
    for i in range(120):
        uk = (b"longprefix-" * 30) + b"%06d" % i  # ~336-byte user keys
        entries.append((make_internal_key(uk, i + 1, ValueType.VALUE),
                        b"v%04d" % i))
    topts = TableOptions(format="zip", filter_policy=None)
    path = str(tmp_path / "lk.sst")
    _build(env, path, entries, topts)
    r = open_table(env.new_random_access_file(path), ICMP, topts)
    assert isinstance(r, ZipTableReader)
    it = r.new_iterator()
    it.seek_to_first()
    assert list(it.entries()) == entries
    it.seek(entries[77][0])
    assert it.valid() and it.key() == entries[77][0]


def test_bad_bottommost_format_fails_at_open(tmp_path):
    from toplingdb_tpu.db.db import DB
    from toplingdb_tpu.options import Options
    from toplingdb_tpu.utils.status import InvalidArgument

    with pytest.raises(InvalidArgument):
        DB.open(str(tmp_path / "x"), Options(bottommost_format="Zip"))


@pytest.mark.parametrize("cut", [False, True])
def test_zip_columnar_writer_byte_parity(tmp_path, monkeypatch, cut):
    """Device compaction with format=zip takes the vectorized columnar zip
    writer; bytes must equal the per-entry CPU path (incl. output cuts)."""
    from toplingdb_tpu.compaction.compaction_job import run_compaction_to_tables
    from toplingdb_tpu.compaction.picker import Compaction
    from toplingdb_tpu.db.table_cache import TableCache
    from toplingdb_tpu.db.version_edit import FileMetaData
    from toplingdb_tpu.env import default_env
    from toplingdb_tpu.ops import device_compaction as dc
    from toplingdb_tpu.ops.device_compaction import run_device_compaction
    from toplingdb_tpu.table.builder import TableBuilder
    from toplingdb_tpu.table import format as zfmt
    import toplingdb_tpu.db.filename as fn

    env = default_env()
    dbdir = str(tmp_path)
    rng = random.Random(31 + cut)
    in_topts = TableOptions(block_size=512)
    out_topts = TableOptions(format="zip", compression=zfmt.ZSTD_COMPRESSION)
    metas = []
    seq = 1
    for fnum in (81, 82, 83):
        entries = []
        for _ in range(400):
            k = b"key%06d" % rng.randrange(600)
            t = (ValueType.VALUE if rng.random() < 0.85
                 else ValueType.DELETION)
            entries.append((make_internal_key(k, seq, t),
                            b"" if t != ValueType.VALUE
                            else b"val%06d" % seq * rng.randrange(1, 3)))
            seq += 1
        entries.sort(key=lambda kv: ICMP.sort_key(kv[0]))
        w = env.new_writable_file(fn.table_file_name(dbdir, fnum))
        b = TableBuilder(w, ICMP, in_topts)
        last = None
        for k, v in entries:
            if k == last:
                continue
            b.add(k, v)
            last = k
        props = b.finish()
        w.close()
        metas.append(FileMetaData(
            number=fnum,
            file_size=env.get_file_size(fn.table_file_name(dbdir, fnum)),
            smallest=b.smallest_key, largest=b.largest_key,
            smallest_seqno=props.smallest_seqno,
            largest_seqno=props.largest_seqno,
        ))
    tc = TableCache(env, dbdir, ICMP, in_topts)
    max_out = 6000 if cut else 1 << 62

    def mk(base):
        st = [base]

        def alloc():
            st[0] += 1
            return st[0]

        return alloc

    c1 = Compaction(level=0, output_level=2, inputs=list(metas),
                    bottommost=True, max_output_file_size=max_out)
    out_cpu, _ = run_compaction_to_tables(
        env, dbdir, ICMP, c1, tc, out_topts, [300], new_file_number=mk(100),
        creation_time=4)

    def no_fallback(*a, **k):
        raise AssertionError("zip columnar path fell back to per-entry")

    monkeypatch.setattr(dc, "collect_raw_entries", no_fallback)
    c2 = Compaction(level=0, output_level=2, inputs=list(metas),
                    bottommost=True, max_output_file_size=max_out)
    out_dev, _ = run_device_compaction(
        env, dbdir, ICMP, c2, tc, out_topts, [300], new_file_number=mk(200),
        creation_time=4, device_name="cpu-jax")
    assert len(out_cpu) == len(out_dev) >= (2 if cut else 1)
    for mc, md in zip(out_cpu, out_dev):
        bc = open(fn.table_file_name(dbdir, mc.number), "rb").read()
        bd = open(fn.table_file_name(dbdir, md.number), "rb").read()
        assert bc == bd, "zip columnar bytes differ from per-entry build"
        assert mc.smallest == md.smallest and mc.largest == md.largest


def test_zip_columnar_tombstone_only_parity(tmp_path):
    """A device job whose entries all GC away but whose range tombstones
    survive must emit the same bytes as the per-entry ZipTableBuilder."""
    import numpy as np

    from toplingdb_tpu.db.range_del import RangeTombstone, fragment_tombstones
    from toplingdb_tpu.env import default_env
    from toplingdb_tpu.ops.columnar_io import ColumnarKV
    from toplingdb_tpu.table.zip_table import write_tables_zip_columnar

    env = default_env()
    topts = TableOptions(format="zip", filter_policy=None)
    frags = fragment_tombstones(
        [RangeTombstone(42, b"aaa", b"mmm")], ICMP.user_comparator)

    # per-entry reference
    p1 = str(tmp_path / "ref.sst")
    w = env.new_writable_file(p1)
    b = new_table_builder(w, ICMP, topts, column_family_name="default")
    for f in frags:
        bb, ee = f.to_table_entry()
        b.add_tombstone(bb, ee)
    b.finish()
    w.close()

    # columnar writer with an empty survivor order
    kv = ColumnarKV(np.zeros(0, np.uint8), np.zeros(0, np.int32),
                    np.zeros(0, np.int32), np.zeros(0, np.uint8),
                    np.zeros(0, np.int32), np.zeros(0, np.int32))
    res = write_tables_zip_columnar(
        env, str(tmp_path), lambda: 7, ICMP, topts, kv,
        np.empty(0, np.int64), np.empty(0, np.int64),
        np.empty(0, np.int32), np.empty(0, np.uint64), frags,
        creation_time=0)
    assert len(res) == 1
    b1 = open(p1, "rb").read()
    b2 = open(res[0][1], "rb").read()
    assert b1 == b2
    assert res[0][2].smallest_seqno == 42
