"""WritePrepared / WriteUnprepared transaction policies.

Reference utilities/transactions/write_prepared_txn_db.cc and
write_unprepared_txn_db.cc: data reaches the DB at Prepare (or earlier, for
unprepared spills), commit is a marker write, and visibility is enforced by
snapshot-checker-style exclusion of undecided seqno ranges.
"""

import pytest

from toplingdb_tpu.options import Options, ReadOptions, WriteOptions
from toplingdb_tpu.utilities.transactions import (
    TransactionDB,
    WritePreparedTransaction,
    WriteUnpreparedTransaction,
)
from toplingdb_tpu.utils.status import InvalidArgument


def wp_open(path, **kw):
    return TransactionDB.open(str(path), Options(),
                              write_policy="write_prepared", **kw)


def test_policy_dispatch(tmp_path):
    tdb = wp_open(tmp_path / "db")
    txn = tdb.begin_transaction()
    assert isinstance(txn, WritePreparedTransaction)
    tdb.close()
    tdb = TransactionDB.open(str(tmp_path / "db2"), Options(),
                             write_policy="write_unprepared")
    assert isinstance(tdb.begin_transaction(), WriteUnpreparedTransaction)
    tdb.close()
    with pytest.raises(InvalidArgument):
        TransactionDB.open(str(tmp_path / "db3"), Options(),
                           write_policy="bogus")


def test_prepared_data_invisible_until_commit(tmp_path):
    tdb = wp_open(tmp_path / "db")
    tdb.put(b"base", b"committed")
    txn = tdb.begin_transaction()
    txn.set_name("t1")
    txn.put(b"k1", b"v1")
    txn.put(b"base", b"overwritten")
    txn.prepare()
    # data is IN the DB now, but invisible to everyone else
    assert tdb.get(b"k1") is None
    assert tdb.get(b"base") == b"committed"
    it = tdb.db.new_iterator(ReadOptions())
    it.seek_to_first()
    assert [k for k, _ in it.entries()] == [b"base"]
    # ... but the txn reads its own writes
    assert txn.get(b"k1") == b"v1"
    txn.commit()
    assert tdb.get(b"k1") == b"v1"
    assert tdb.get(b"base") == b"overwritten"
    tdb.close()


def test_snapshot_taken_during_prepare_never_sees_data(tmp_path):
    tdb = wp_open(tmp_path / "db")
    txn = tdb.begin_transaction()
    txn.set_name("t1")
    txn.put(b"x", b"txn-value")
    txn.prepare()
    snap = tdb.db.get_snapshot()          # while undecided
    txn.commit()
    # the commit point is after the snapshot: still invisible to it
    assert tdb.get(b"x", ReadOptions(snapshot=snap)) is None
    assert tdb.get(b"x") == b"txn-value"  # fresh read sees it
    snap.release()
    tdb.close()


def test_rollback_restores_previous_values(tmp_path):
    tdb = wp_open(tmp_path / "db")
    tdb.put(b"a", b"old-a")
    txn = tdb.begin_transaction()
    txn.set_name("t1")
    txn.put(b"a", b"new-a")
    txn.put(b"b", b"new-b")
    txn.delete(b"a")  # multiple ops on same txn
    txn.prepare()
    txn.rollback()
    assert tdb.get(b"a") == b"old-a"
    assert tdb.get(b"b") is None
    # locks released: another txn can write immediately
    t2 = tdb.begin_transaction()
    t2.put(b"a", b"after")
    t2.commit()
    assert tdb.get(b"a") == b"after"
    tdb.close()


def test_commit_without_prepare_is_atomic_write(tmp_path):
    tdb = wp_open(tmp_path / "db")
    txn = tdb.begin_transaction()
    txn.put(b"k", b"v")
    txn.commit()
    assert tdb.get(b"k") == b"v"
    tdb.close()


def test_recovery_of_prepared_txn(tmp_path):
    tdb = wp_open(tmp_path / "db")
    txn = tdb.begin_transaction()
    txn.set_name("crashy")
    txn.put(b"pending", b"data")
    txn.prepare()
    tdb.db.close()  # abrupt-ish: no commit/rollback decision

    tdb = wp_open(tmp_path / "db")
    # undecided data stays invisible after recovery
    assert tdb.get(b"pending") is None
    recovered = tdb.get_prepared_transactions()
    assert len(recovered) == 1 and recovered[0].name == "crashy"
    recovered[0].commit()
    assert tdb.get(b"pending") == b"data"
    tdb.close()
    # decision survives another reopen
    tdb = wp_open(tmp_path / "db")
    assert tdb.get(b"pending") == b"data"
    assert not tdb.get_prepared_transactions()
    tdb.close()


def test_recovery_rollback_of_prepared_txn(tmp_path):
    tdb = wp_open(tmp_path / "db")
    tdb.put(b"k", b"original")
    txn = tdb.begin_transaction()
    txn.set_name("crashy")
    txn.put(b"k", b"uncommitted")
    txn.prepare()
    tdb.db.close()

    tdb = wp_open(tmp_path / "db")
    assert tdb.get(b"k") == b"original"
    tdb.get_prepared_transactions()[0].rollback()
    assert tdb.get(b"k") == b"original"
    tdb.close()
    tdb = wp_open(tmp_path / "db")
    assert tdb.get(b"k") == b"original"
    tdb.close()


def test_prepared_survives_flush_and_compaction(tmp_path):
    tdb = wp_open(tmp_path / "db")
    for i in range(100):
        tdb.put(b"w%03d" % i, b"v%d" % i)
    txn = tdb.begin_transaction()
    txn.set_name("t1")
    txn.put(b"w050", b"pending")
    txn.prepare()
    tdb.db.flush()
    tdb.db.compact_range()
    assert tdb.get(b"w050") == b"v50"  # still the committed value
    txn.commit()
    assert tdb.get(b"w050") == b"pending"
    tdb.close()


def test_unprepared_spills_stay_invisible(tmp_path):
    tdb = TransactionDB.open(str(tmp_path / "db"), Options(),
                             write_policy="write_unprepared")
    txn = tdb.begin_transaction()
    txn.spill_threshold = 256  # force frequent spills
    big = b"x" * 64
    for i in range(50):
        txn.put(b"big%03d" % i, big)
    assert txn._spill_off is not None, "expected at least one spill"
    # spilled data invisible to outside readers
    assert tdb.get(b"big000") is None
    # read-your-own-writes across spills
    assert txn.get(b"big000") == big
    txn.commit()
    assert tdb.get(b"big049") == big
    tdb.close()


def test_unprepared_rollback_and_crash_abort(tmp_path):
    tdb = TransactionDB.open(str(tmp_path / "db"), Options(),
                             write_policy="write_unprepared")
    tdb.put(b"big000", b"pre-existing")
    txn = tdb.begin_transaction()
    txn.spill_threshold = 128
    for i in range(30):
        txn.put(b"big%03d" % i, b"y" * 64)
    assert txn._spill_off is not None
    txn.rollback()
    assert tdb.get(b"big000") == b"pre-existing"
    assert tdb.get(b"big001") is None

    # crash with spilled-but-never-prepared data → auto-abort at recovery
    txn2 = tdb.begin_transaction()
    txn2.spill_threshold = 128
    for i in range(30):
        txn2.put(b"crash%03d" % i, b"z" * 64)
    assert txn2._spill_off is not None
    tdb.db.close()  # no decision
    tdb = TransactionDB.open(str(tmp_path / "db"), Options(),
                             write_policy="write_unprepared")
    assert tdb.get(b"crash000") is None
    assert tdb.get(b"big000") == b"pre-existing"
    assert not tdb.get_prepared_transactions()  # aborted, not recovered
    tdb.close()


def test_snapshot_exclusion_survives_commit_and_compaction(tmp_path):
    """A snapshot taken while a txn is prepared must read the PRE-txn value
    even after the txn commits and compaction runs (the parked compaction
    guard keeps the old version alive)."""
    tdb = wp_open(tmp_path / "db")
    tdb.put(b"k", b"pre")
    txn = tdb.begin_transaction()
    txn.set_name("t1")
    txn.put(b"k", b"txn")
    txn.prepare()
    snap = tdb.db.get_snapshot()
    txn.commit()  # guard must be parked: snap still excludes [lo, hi]
    tdb.db.flush()
    tdb.db.compact_range()
    assert tdb.get(b"k", ReadOptions(snapshot=snap)) == b"pre"
    assert tdb.get(b"k") == b"txn"
    snap.release()
    # next txn op sweeps the parked guard
    tdb.begin_transaction().rollback()
    assert not tdb._parked_guards
    tdb.close()


def test_reserved_rb_names_rejected(tmp_path):
    tdb = wp_open(tmp_path / "db")
    txn = tdb.begin_transaction()
    with pytest.raises(InvalidArgument):
        txn.set_name("rb.evil")
    tdb.close()


def test_wp_and_wc_conflict_isolation(tmp_path):
    """Locks still guard across policies: a prepared WP txn holds its keys."""
    tdb = wp_open(tmp_path / "db")
    txn = tdb.begin_transaction()
    txn.set_name("holder")
    txn.put(b"locked", b"v")
    txn.prepare()
    t2 = tdb.begin_transaction(lock_timeout=0.05)
    from toplingdb_tpu.utils.status import Busy

    with pytest.raises(Busy):
        t2.put(b"locked", b"other")
    txn.commit()
    t2.put(b"locked", b"other")
    t2.commit()
    assert tdb.get(b"locked") == b"other"
    tdb.close()
