import pytest

from toplingdb_tpu.utils import coding, crc32c
from toplingdb_tpu.utils.status import Corruption


def test_fixed_roundtrip():
    assert coding.decode_fixed32(coding.encode_fixed32(0xDEADBEEF)) == 0xDEADBEEF
    assert coding.decode_fixed64(coding.encode_fixed64(2**56 + 7)) == 2**56 + 7
    assert coding.encode_fixed32(1) == b"\x01\x00\x00\x00"


@pytest.mark.parametrize(
    "v", [0, 1, 127, 128, 300, 2**21, 2**28 - 1, 2**32 - 1, 2**56, 2**64 - 1]
)
def test_varint_roundtrip(v):
    enc = coding.encode_varint64(v)
    dec, off = coding.decode_varint64(enc)
    assert dec == v
    assert off == len(enc)
    assert coding.varint_length(v) == len(enc)


def test_varint_truncated():
    with pytest.raises(Corruption):
        coding.decode_varint64(b"\x80")


def test_length_prefixed():
    out = bytearray()
    coding.put_length_prefixed_slice(out, b"hello")
    coding.put_length_prefixed_slice(out, b"")
    s1, off = coding.get_length_prefixed_slice(out, 0)
    s2, off = coding.get_length_prefixed_slice(out, off)
    assert s1 == b"hello" and s2 == b"" and off == len(out)


# CRC32C known-answer tests (Castagnoli standard vectors).
def test_crc32c_vectors():
    assert crc32c.value(b"") == 0
    assert crc32c.value(b"123456789") == 0xE3069283
    assert crc32c.value(bytes(32)) == 0x8A9136AA
    assert crc32c.value(bytes([0xFF] * 32)) == 0x62A8AB43


def test_crc32c_extend_composes():
    data = b"hello world, this is a crc composition test"
    whole = crc32c.value(data)
    part = crc32c.extend(crc32c.value(data[:10]), data[10:])
    assert whole == part


def test_crc_mask_roundtrip():
    c = crc32c.value(b"foo")
    assert crc32c.mask(c) != c
    assert crc32c.unmask(crc32c.mask(c)) == c


def test_native_matches_python_fallback():
    from toplingdb_tpu import native
    from toplingdb_tpu.utils.crc32c import _table

    if native.lib() is None:
        pytest.skip("native lib unavailable")
    data = bytes(range(256)) * 7 + b"tail"
    t = _table()
    c = 0xFFFFFFFF
    for b in data:
        c = t[(c ^ b) & 0xFF] ^ (c >> 8)
    py = (c ^ 0xFFFFFFFF) & 0xFFFFFFFF
    assert crc32c.value(data) == py


def test_xxh64_known_answers():
    # Public xxh64 test vectors.
    assert crc32c.xxh64(b"", 0) == 0xEF46DB3751D8E999
    assert crc32c.xxh64(b"a", 0) == 0xD24EC4F1A98C6E5B
    assert crc32c.xxh64(b"abc", 0) == 0x44BC2CF5AD770999
    assert (
        crc32c.xxh64(b"Nobody inspects the spammish repetition", 0)
        == 0xFBCEA83C8A378BF1
    )
