"""Replication plane: WAL shipping, follower DBs, bounded-staleness router.

Covers the acceptance matrix of the replication subsystem:
  - frame encode/decode + corruption detection
  - primary/follower byte-parity after convergence (shared + standalone)
  - read-your-writes token guarantee (no read observes applied < token)
  - bootstrap-after-WAL-GC through Checkpoint.restore_to
  - chaos soak: 30% drop/delay/truncate of shipped batches still converges
    to byte parity with the primary's checkpoint
  - HTTP transport / ReplicationServer / SidePlugin views / promote
  - SecondaryDB catch-up across CF create/drop and WAL deletion
"""

import json
import threading
import urllib.request

import pytest

from toplingdb_tpu.db.db import DB
from toplingdb_tpu.options import Options
from toplingdb_tpu.replication import (
    FaultyTransport,
    FollowerDB,
    HttpTransport,
    LocalTransport,
    LogShipper,
    ReplicaRouter,
    ReplicationServer,
    ShipFrame,
    WalRetentionGone,
)
from toplingdb_tpu.utils.statistics import Statistics
from toplingdb_tpu.utils.status import Corruption


def opts(**kw):
    kw.setdefault("write_buffer_size", 1 << 20)
    kw.setdefault("statistics", Statistics())
    return Options(**kw)


def dump(db):
    """Full user-visible content across every CF: the parity fingerprint."""
    out = []
    for handle in sorted(db.list_column_families(), key=lambda h: h.id):
        it = db.new_iterator(cf=handle)
        it.seek_to_first()
        rows = []
        while it.valid():
            rows.append((it.key(), it.value()))
            it.next()
        out.append((handle.id, handle.name, rows))
    return out


# -- frame format ------------------------------------------------------------


def test_frame_roundtrip():
    f = ShipFrame(epoch=7, first_seq=10, last_seq=42,
                  shipped_unix_us=123456, batches=[b"abc", b"", b"x" * 999])
    g = ShipFrame.decode(f.encode())
    assert (g.epoch, g.first_seq, g.last_seq, g.shipped_unix_us,
            g.batches) == (7, 10, 42, 123456, [b"abc", b"", b"x" * 999])


def test_frame_detects_truncation_and_bitflips():
    f = ShipFrame(epoch=1, first_seq=1, last_seq=3,
                  shipped_unix_us=0, batches=[b"payload-bytes" * 10])
    enc = f.encode()
    for cut in (0, 4, len(enc) // 2, len(enc) - 1):
        with pytest.raises(Corruption):
            ShipFrame.decode(enc[:cut])
    flipped = bytearray(enc)
    flipped[len(enc) - 3] ^= 0x40  # payload bitflip → CRC mismatch
    with pytest.raises(Corruption):
        ShipFrame.decode(bytes(flipped))


# -- shipper -----------------------------------------------------------------


def test_shipper_serves_and_detects_retention_gone(tmp_path):
    db = DB.open(str(tmp_path / "db"), opts(create_if_missing=True))
    ship = LogShipper(db)
    for i in range(20):
        db.put(b"k%02d" % i, b"v%02d" % i)
    frames, state = ship.frames_since(0)
    assert frames and frames[0].first_seq == 1
    assert frames[-1].last_seq == state["last_sequence"] == 20
    # Already-applied cursor → empty.
    frames, _ = ship.frames_since(20)
    assert frames == []
    # Flush twice so the WAL holding seqs 1..20 is GC'd.
    db.flush()
    for i in range(5):
        db.put(b"x%02d" % i, b"y")
    db.flush()
    db.put(b"tail", b"t")
    with pytest.raises(WalRetentionGone):
        ship.frames_since(3)
    db.close()


# -- follower convergence ----------------------------------------------------


def test_follower_shared_parity_and_epoch_reload(tmp_path):
    src = str(tmp_path / "db")
    db = DB.open(src, opts(create_if_missing=True))
    ship = LogShipper(db)
    fol = FollowerDB.open(src, Options(statistics=db.stats),
                          transport=LocalTransport(ship), mode="shared")
    for i in range(50):
        db.put(b"a%03d" % i, b"v%03d" % i)
    fol.catch_up()
    assert fol.get(b"a025") == b"v025"
    # Flush + compact installs new versions → epoch reload path.
    db.flush()
    for i in range(50):
        db.put(b"a%03d" % i, b"w%03d" % i)  # overwrite
    db.delete(b"a000")
    db.flush()
    db.compact_range()
    for _ in range(4):
        fol.catch_up()
    assert fol.get(b"a000") is None
    assert fol.get(b"a001") == b"w001"
    assert dump(fol) == dump(db)
    st = fol.replication_status()
    assert st["role"] == "follower"
    assert st["applied_sequence"] == db.versions.last_sequence
    assert db.stats.get_ticker_count(
        "replication.epoch.reloads") >= 1
    fol.close()
    db.close()


def test_follower_standalone_bootstrap_after_wal_gc(tmp_path):
    src, fdir = str(tmp_path / "db"), str(tmp_path / "fol")
    db = DB.open(src, opts(create_if_missing=True))
    ship = LogShipper(db)
    tr = LocalTransport(ship)
    for i in range(30):
        db.put(b"k%03d" % i, b"v%03d" % i)
    fol = FollowerDB.open(fdir, Options(statistics=db.stats),
                          transport=tr, mode="standalone")
    assert fol.get(b"k010") == b"v010"  # bootstrapped via Checkpoint.restore_to
    # Live tail keeps flowing.
    db.put(b"live", b"1")
    fol.catch_up()
    assert fol.get(b"live") == b"1"
    # Outrun WAL retention: two flush cycles delete the WALs the
    # follower's cursor would need → automatic re-bootstrap.
    db.flush()
    for i in range(40):
        db.put(b"g%03d" % i, b"w%03d" % i)
    db.flush()
    db.put(b"tail", b"t")
    for _ in range(4):
        fol.catch_up()
    assert fol.get(b"g020") == b"w020"
    assert fol.get(b"tail") == b"t"
    assert fol.applied_sequence() == db.versions.last_sequence
    assert db.stats.get_ticker_count("replication.bootstraps") >= 1
    assert dump(fol) == dump(db)
    fol.close()
    db.close()


# -- router: tokens, staleness, health ---------------------------------------


def test_router_read_your_writes_token(tmp_path):
    src = str(tmp_path / "db")
    db = DB.open(src, opts(create_if_missing=True))
    ship = LogShipper(db)
    fol = FollowerDB.open(src, transport=LocalTransport(ship), mode="shared")
    router = ReplicaRouter(db, [fol])
    stats = db.stats
    token = router.put(b"k", b"v1")
    assert token == db.versions.last_sequence
    # Follower has NOT caught up: a token read must not serve stale data —
    # it falls back to the primary.
    assert router.get(b"k", token=token) == b"v1"
    assert stats.get_ticker_count("replication.router.primary.reads") == 1
    assert stats.get_ticker_count("replication.router.stale.skips") == 1
    # After catch-up the same token read is served by the follower.
    fol.catch_up()
    assert fol.applied_sequence() >= token
    assert router.get(b"k", token=token) == b"v1"
    assert stats.get_ticker_count("replication.router.follower.reads") == 1
    # Token-less reads always accept the follower.
    assert router.get(b"k") == b"v1"
    # multi_get honours tokens the same way.
    t2 = router.put(b"k2", b"v2")
    assert router.multi_get([b"k", b"k2"], token=t2) == [b"v1", b"v2"]
    fol.catch_up()
    assert router.multi_get([b"k", b"k2"], token=t2) == [b"v1", b"v2"]
    # Iterators: stale follower skipped for token-carrying scans.
    t3 = router.put(b"k3", b"v3")
    it = router.new_iterator(token=t3)
    it.seek(b"k3")
    assert it.valid() and it.value() == b"v3"
    fol.close()
    db.close()


def test_router_breaker_skips_failing_follower(tmp_path):
    src = str(tmp_path / "db")
    db = DB.open(src, opts(create_if_missing=True))
    db.put(b"k", b"v")

    class BrokenReplica:
        def applied_sequence(self):
            return 1 << 60  # always "fresh" — only reads fail

        def get(self, *a, **kw):
            raise RuntimeError("replica down")

        def multi_get(self, *a, **kw):
            raise RuntimeError("replica down")

        def new_iterator(self, *a, **kw):
            raise RuntimeError("replica down")

    from toplingdb_tpu.replication.router import RouterOptions

    router = ReplicaRouter(db, [BrokenReplica()],
                           RouterOptions(breaker_failure_threshold=2,
                                         breaker_reset_timeout=3600.0))
    for _ in range(4):
        assert router.get(b"k") == b"v"  # served by primary fallback
    # After 2 consecutive failures the breaker opens: later reads skip the
    # replica without even trying it.
    assert db.stats.get_ticker_count(
        "replication.router.breaker.skips") >= 1
    snap = router.status()["health"]
    assert list(snap.values())[0]["state"] == "open"
    db.close()


def test_router_max_lag_bound(tmp_path):
    from toplingdb_tpu.replication.router import RouterOptions

    src = str(tmp_path / "db")
    db = DB.open(src, opts(create_if_missing=True))
    ship = LogShipper(db)
    fol = FollowerDB.open(src, transport=LocalTransport(ship), mode="shared")
    router = ReplicaRouter(db, [fol], RouterOptions(max_lag_seq=5))
    for i in range(20):
        db.put(b"k%02d" % i, b"v")
    # Follower is 20 seqs behind: token-less reads still must not use it.
    assert router.get(b"k00") == b"v"
    assert db.stats.get_ticker_count("replication.router.stale.skips") >= 1
    fol.catch_up()
    assert router.get(b"k00") == b"v"
    assert db.stats.get_ticker_count(
        "replication.router.follower.reads") >= 1
    fol.close()
    db.close()


# -- chaos soak --------------------------------------------------------------


@pytest.mark.parametrize("mode", ["shared", "standalone"])
def test_chaos_soak_converges_to_checkpoint_parity(tmp_path, mode):
    """30% injected ship-transport faults (drop/delay/truncate): the
    follower still converges to byte-identical state vs the primary's
    checkpoint, and token-carrying router reads never observe a sequence
    older than their token."""
    from toplingdb_tpu.db.db_readonly import ReadOnlyDB
    from toplingdb_tpu.env.fault_injection import ShipFaultInjector

    src = str(tmp_path / "db")
    fdir = src if mode == "shared" else str(tmp_path / "fol")
    db = DB.open(src, opts(create_if_missing=True))
    ship = LogShipper(db)
    injector = ShipFaultInjector(rate=0.30, seed=1234, delay_sec=0.001)
    transport = FaultyTransport(LocalTransport(ship), injector)
    fol = FollowerDB.open(fdir, Options(statistics=db.stats),
                          transport=transport, mode=mode)
    router = ReplicaRouter(db, [fol])

    import random

    rng = random.Random(99)
    expected = {}
    for round_no in range(30):
        # A burst of writes; every 10th round a flush (epoch churn + WAL GC
        # pressure so retention-gone paths fire under fault load too).
        for _ in range(20):
            k = b"key%03d" % rng.randrange(200)
            if rng.random() < 0.15 and k in expected:
                token = router.delete(k)
                expected.pop(k, None)
            else:
                v = b"val%06d" % rng.randrange(1 << 20)
                token = router.put(k, v)
                expected[k] = v
            if rng.random() < 0.3:
                # Read-your-writes probe THROUGH the fault storm: the
                # router must never serve a pre-token view of this key.
                got = router.get(k, token=token)
                assert got == expected.get(k), (round_no, k)
        if round_no % 10 == 9:
            db.flush()
        fol.catch_up()
    # Faults actually fired at meaningful volume.
    counts = injector.injected_counts()
    assert sum(counts.values()) >= 10, counts
    # Drain: enough rounds that the (seeded) fault stream lets the tail
    # through; drop/truncate rounds make no progress, they never corrupt.
    for _ in range(60):
        fol.catch_up()
        if fol.applied_sequence() == db.versions.last_sequence:
            break
    assert fol.applied_sequence() == db.versions.last_sequence
    # Byte-parity vs the primary's CHECKPOINT (the acceptance criterion:
    # a frozen, openable snapshot of the primary's state).
    from toplingdb_tpu.utilities.checkpoint import Checkpoint

    ckpt_dir = str(tmp_path / "ckpt")
    Checkpoint.create(db, ckpt_dir)
    ck = ReadOnlyDB.open(ckpt_dir)
    try:
        follower_view = {k: v for _, _, rows in dump(fol) for k, v in rows}
        ckpt_view = {k: v for _, _, rows in dump(ck) for k, v in rows}
        assert follower_view == ckpt_view == expected
    finally:
        ck.close()
    # Corrupted (truncated) frames were detected, counted, and never
    # half-applied.
    if counts.get("truncate"):
        assert db.stats.get_ticker_count("replication.frame.corrupt") >= 1
    assert db.stats.get_histogram("replication.lag.micros").count >= 1
    fol.close()
    db.close()


# -- background tailing ------------------------------------------------------


def test_background_tailing_with_concurrent_writes(tmp_path):
    src = str(tmp_path / "db")
    db = DB.open(src, opts(create_if_missing=True))
    ship = LogShipper(db)
    fol = FollowerDB.open(src, transport=LocalTransport(ship), mode="shared")
    fol.start_tailing(interval=0.005)
    router = ReplicaRouter(db, [fol])
    errors = []

    def writer(tid):
        try:
            for i in range(150):
                token = router.put(b"t%d-%03d" % (tid, i), b"v%03d" % i)
                if i % 20 == 0:
                    got = router.get(b"t%d-%03d" % (tid, i), token=token)
                    assert got == b"v%03d" % i
        except Exception as e:  # noqa: BLE001
            errors.append(e)

    threads = [threading.Thread(target=writer, args=(t,)) for t in range(3)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    assert not errors
    deadline = 100
    while (fol.applied_sequence() != db.versions.last_sequence
           and deadline > 0):
        import time

        time.sleep(0.02)
        deadline -= 1
    assert fol.applied_sequence() == db.versions.last_sequence
    fol.stop_tailing()
    assert dump(fol) == dump(db)
    fol.close()
    db.close()


# -- HTTP plane --------------------------------------------------------------


def test_http_transport_and_replication_server(tmp_path):
    src, fdir = str(tmp_path / "db"), str(tmp_path / "fol")
    db = DB.open(src, opts(create_if_missing=True))
    srv = ReplicationServer(db)
    port = srv.start()
    try:
        for i in range(25):
            db.put(b"h%03d" % i, b"v%03d" % i)
        tr = HttpTransport(f"http://127.0.0.1:{port}")
        fol = FollowerDB.open(fdir, transport=tr, mode="standalone")
        assert fol.get(b"h011") == b"v011"
        db.put(b"after", b"x")
        fol.catch_up()
        assert fol.get(b"after") == b"x"
        assert dump(fol) == dump(db)
        # Status endpoint serves shipper introspection.
        with urllib.request.urlopen(
                f"http://127.0.0.1:{port}/replication/status") as r:
            st = json.loads(r.read())
        assert st["role"] == "primary"
        assert st["last_sequence"] == db.versions.last_sequence
        fol.close()
    finally:
        srv.stop()
        db.close()


def test_sideplugin_replication_view_and_promote(tmp_path):
    from toplingdb_tpu.utils.config import SidePluginRepo

    src = str(tmp_path / "db")
    repo = SidePluginRepo()
    db = repo.open_db({"path": src,
                       "options": {"create_if_missing": True}}, name="prim")
    ship = LogShipper(db)
    fol = FollowerDB.open(src, transport=LocalTransport(ship), mode="shared")
    repo.attach_db("fol", fol, {"options": {}})
    port = repo.start_http()
    base = f"http://127.0.0.1:{port}"
    try:
        db.put(b"a", b"1")
        fol.catch_up()
        with urllib.request.urlopen(f"{base}/replication/prim") as r:
            prim = json.loads(r.read())
        assert prim["role"] == "primary"
        assert prim["frames_shipped"] >= 1
        with urllib.request.urlopen(f"{base}/replication/fol") as r:
            fv = json.loads(r.read())
        assert fv["role"] == "follower"
        assert fv["applied_sequence"] == db.versions.last_sequence

        # repl_admin CLI against the same endpoints.
        from toplingdb_tpu.tools.repl_admin import main as admin_main

        assert admin_main(["--url", base, "status"]) == 0
        assert admin_main(["--url", base, "lag", "--max-lag", "1000"]) == 0

        # Promote: the primary "dies"; the follower becomes read-write.
        db.close()
        assert admin_main(["--url", base, "promote", "--db", "fol"]) == 0
        promoted = repo.get_db("fol")
        assert promoted is not fol
        promoted.put(b"post-promote", b"yes")  # read-write now
        assert promoted.get(b"a") == b"1"
        with urllib.request.urlopen(f"{base}/replication/fol") as r:
            pv = json.loads(r.read())
        assert pv["role"] == "primary-unshipped"
    finally:
        repo.stop_http()
        for name in ("fol",):
            d = repo.get_db(name)
            if d is not None:
                d.close()


# -- SecondaryDB satellite fixes ---------------------------------------------


def test_secondary_catchup_cf_created_and_dropped(tmp_path):
    from toplingdb_tpu.db.db_readonly import SecondaryDB

    src = str(tmp_path / "db")
    db = DB.open(src, opts(create_if_missing=True))
    doomed = db.create_column_family("doomed")
    db.put(b"d", b"1", cf=doomed)
    db.put(b"k", b"v")
    db.flush()
    sec = SecondaryDB.open(src)
    assert sec.get(b"k") == b"v"
    assert sec.get(b"d", cf=1) == b"1"
    # Primary drops one CF and creates another between catch-ups.
    db.drop_column_family(doomed)
    newcf = db.create_column_family("fresh")
    db.put(b"f", b"2", cf=newcf)
    sec.try_catch_up_with_primary()
    names = {h.name for h in sec.list_column_families()}
    assert "doomed" not in names and "fresh" in names
    fresh = sec.get_column_family("fresh")
    assert sec.get(b"f", cf=fresh) == b"2"
    sec.close()
    db.close()


def test_secondary_catchup_survives_wal_gc_and_drops_stale_mem(tmp_path):
    """Flush+GC between catch-ups: deleted WALs are skipped, and stale
    memtable entries from the PREVIOUS catch-up don't shadow the SSTs."""
    from toplingdb_tpu.db.db_readonly import SecondaryDB

    src = str(tmp_path / "db")
    db = DB.open(src, opts(create_if_missing=True))
    db.put(b"k", b"old")
    sec = SecondaryDB.open(src)
    assert sec.get(b"k") == b"old"
    db.put(b"k", b"mid")
    db.delete(b"k")
    db.flush()          # WAL with "old"/"mid"/delete is GC'd
    db.compact_range()  # tombstone compacted away
    sec.try_catch_up_with_primary()
    # A stale memtable carry-over would resurrect "old"/"mid" here.
    assert sec.get(b"k") is None
    db.put(b"k", b"new")
    sec.try_catch_up_with_primary()
    assert sec.get(b"k") == b"new"
    sec.close()
    db.close()


# -- checkpoint satellite ----------------------------------------------------


def test_checkpoint_includes_options_and_current_last(tmp_path):
    from toplingdb_tpu.utilities.checkpoint import Checkpoint

    from toplingdb_tpu.table import format as fmt

    src, dst = str(tmp_path / "db"), str(tmp_path / "ck")
    db = DB.open(src, opts(create_if_missing=True,
                           compression=fmt.ZLIB_COMPRESSION))
    for i in range(10):
        db.put(b"c%02d" % i, b"v")
    ck = Checkpoint.create(db, dst)
    import os

    names = sorted(os.listdir(dst))
    assert "CURRENT" in names
    assert any(n.startswith("OPTIONS-") for n in names), names
    ck.verify()
    # restore_to yields an independently openable copy.
    restored = ck.restore_to(str(tmp_path / "restored"))
    db.close()
    # OPTIONS carried configuration, not just data (probe BEFORE opening:
    # a fresh open persists the opener's own OPTIONS on top).
    from toplingdb_tpu.utils.config import load_latest_options

    lo = load_latest_options(restored)
    assert lo is not None and lo.compression == fmt.ZLIB_COMPRESSION
    with DB.open(restored, lo) as rdb:
        assert rdb.get(b"c05") == b"v"


def test_checkpoint_restore_refuses_partial(tmp_path):
    from toplingdb_tpu.utilities.checkpoint import Checkpoint
    from toplingdb_tpu.utils.status import InvalidArgument

    src, dst = str(tmp_path / "db"), str(tmp_path / "ck")
    db = DB.open(src, opts(create_if_missing=True))
    db.put(b"a", b"1")
    Checkpoint.create(db, dst)
    db.close()
    import os

    os.remove(os.path.join(dst, "CURRENT"))  # interrupted create
    with pytest.raises(InvalidArgument):
        Checkpoint(dst).restore_to(str(tmp_path / "nope"))
