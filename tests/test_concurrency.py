"""Concurrency-correctness plane (static analyzer + runtime debug layer).

Static: `tools/check_concurrency.py` must pass over the real tree, and
must catch seeded violations on synthetic trees — a direct lock-order
cycle, a cross-function cycle through call resolution, thread-lifecycle
lint (raw primitives, unnamed spawns, no join path), and lock-hierarchy
enforcement against a declared table.

Runtime: TPULSM_LOCK_DEBUG wrappers — induced inversion raises
LockInversionError carrying BOTH stacks, the watchdog reports long
holds, scan_long_holds finds a wedged holder, Condition-over-wrapper
keeps the held-set honest across wait(), the ThreadRegistry catches an
unstopped scrubber-style thread through DB.close(), and a clean
open/write/close leaves nothing registered.
"""

import textwrap
import threading
import time
import warnings

import pytest

from toplingdb_tpu.db.db import DB
from toplingdb_tpu.options import FlushOptions, Options
from toplingdb_tpu.tools import check_concurrency as cc
from toplingdb_tpu.utils import concurrency as ccy

# ---------------------------------------------------------------------------
# Static analyzer: the real tree
# ---------------------------------------------------------------------------


def test_tree_is_clean_and_nonempty():
    ana = cc.analyze()
    assert ana.violations == []
    # The model actually saw the tree (not a silently-empty walk).
    assert len(ana.lock_sites) >= 50
    assert len(ana.edges) >= 15


def test_cli_exits_zero_on_clean_tree(capsys):
    assert cc.main([]) == 0
    out = capsys.readouterr().out
    assert "check_concurrency:" in out
    assert "0 violation(s)" in out


# ---------------------------------------------------------------------------
# Static analyzer: seeded violations on synthetic trees
# ---------------------------------------------------------------------------


def _lint(tmp_path, files):
    for name, src in files.items():
        (tmp_path / name).write_text(textwrap.dedent(src))
    return cc.run(str(tmp_path))


def test_detects_seeded_lock_order_cycle(tmp_path):
    out = _lint(tmp_path, {"m.py": """\
        from toplingdb_tpu.utils import concurrency as ccy


        class X:
            def __init__(self):
                self._a = ccy.Lock("m.X._a")
                self._b = ccy.Lock("m.X._b")

            def ab(self):
                with self._a:
                    with self._b:
                        pass

            def ba(self):
                with self._b:
                    with self._a:
                        pass
        """})
    cycles = [v for v in out if "lock-order cycle" in v]
    assert len(cycles) == 1, out
    assert "m.X._a" in cycles[0] and "m.X._b" in cycles[0]
    assert "m.py:" in cycles[0]  # every edge carries a witness site


def test_detects_cross_function_cycle(tmp_path):
    """The cycle only exists through call resolution: fwd() holds _front
    and CALLS take_back(); rev() holds _back and CALLS take_front()."""
    out = _lint(tmp_path, {"n.py": """\
        from toplingdb_tpu.utils import concurrency as ccy


        class Y:
            def __init__(self):
                self._front = ccy.Lock("n.Y._front")
                self._back = ccy.Lock("n.Y._back")

            def take_back(self):
                with self._back:
                    pass

            def fwd(self):
                with self._front:
                    self.take_back()

            def take_front(self):
                with self._front:
                    pass

            def rev(self):
                with self._back:
                    self.take_front()
        """})
    cycles = [v for v in out if "lock-order cycle" in v]
    assert len(cycles) == 1, out
    assert "n.Y._front" in cycles[0] and "n.Y._back" in cycles[0]


def test_thread_lifecycle_lint(tmp_path):
    out = _lint(tmp_path, {"t.py": """\
        import threading

        from toplingdb_tpu.utils import concurrency as ccy


        def _work():
            pass


        def bad_raw():
            t = threading.Thread(target=_work)
            t.start()


        def bad_unjoined():
            ccy.spawn("t-orphan", _work)


        def bad_unnamed(name):
            ccy.spawn(name, _work, owner=object())


        def good_owned(db):
            ccy.spawn("t-owned", _work, owner=db)


        def good_joined():
            t = ccy.spawn("t-joined", _work)
            t.join()
        """})
    assert len([v for v in out if "raw threading" in v]) == 1, out
    assert len([v for v in out if "no join path" in v]) == 1, out
    assert len([v for v in out if "literal" in v]) == 1, out
    assert len(out) == 3, out  # the two good spawns are NOT flagged


def test_hierarchy_enforcement(tmp_path):
    (tmp_path / "ARCHITECTURE.md").write_text(textwrap.dedent("""\
        ## Lock hierarchy

        | Rank | Lock class | Guards |
        |------|------------|--------|
        | 1 | `h.Z._outer` | outer state |
        | 2 | `h.Z._inner` | inner state |
        | 1 | `h.Z._gone` | stale row |
        """))
    out = _lint(tmp_path, {"h.py": """\
        from toplingdb_tpu.utils import concurrency as ccy


        class Z:
            def __init__(self):
                self._outer = ccy.Lock("h.Z._outer")
                self._inner = ccy.Lock("h.Z._inner")
                self._extra = ccy.Lock("h.Z._extra")

            def wrong_order(self):
                with self._inner:
                    with self._outer:
                        pass
        """})
    assert any("h.Z._extra" in v and "not declared" in v for v in out), out
    assert any("h.Z._gone" in v and "no longer exists" in v for v in out), out
    assert any("violates the declared lock hierarchy" in v and
               "h.Z._inner" in v for v in out), out


def test_bare_acquire_release_flagged(tmp_path):
    out = _lint(tmp_path, {"q.py": """\
        from toplingdb_tpu.utils import concurrency as ccy


        class W:
            def __init__(self):
                self._mu = ccy.Lock("q.W._mu")

            def manual(self):
                self._mu.acquire()
                try:
                    pass
                finally:
                    self._mu.release()
        """})
    assert any("acquire" in v for v in out), out


# ---------------------------------------------------------------------------
# Runtime debug layer
# ---------------------------------------------------------------------------


@pytest.fixture
def debug_locks():
    ccy.reset_lock_graph()
    ccy.set_debug(True)
    yield
    ccy.set_debug(False)
    ccy.reset_lock_graph()
    ccy.set_watchdog_ms(30000)
    ccy.set_watchdog_handler(None)


def test_induced_inversion_raises_with_both_stacks(debug_locks):
    a = ccy.Lock("test.inv.A")
    b = ccy.Lock("test.inv.B")
    with a:
        with b:
            pass
    assert ("test.inv.A", "test.inv.B") in ccy.lock_order_edges()
    with pytest.raises(ccy.LockInversionError) as ei:
        with b:
            with a:
                pass
    msg = str(ei.value)
    assert "test.inv.A" in msg and "test.inv.B" in msg
    assert "acquiring stack" in msg
    assert "witness" in msg
    # Both stacks point at this test file.
    assert msg.count("test_concurrency.py") >= 2
    # The failed acquisition did not leave an orphaned hold.
    assert ccy.held_lock_classes() == []
    with a:  # still usable after the raise
        pass


def test_transitive_inversion_detected(debug_locks):
    a, b, c = (ccy.Lock("test.tri.A"), ccy.Lock("test.tri.B"),
               ccy.Lock("test.tri.C"))
    with a:
        with b:
            pass
    with b:
        with c:
            pass
    with pytest.raises(ccy.LockInversionError) as ei:
        with c:
            with a:
                pass
    # The witness chain spells out the recorded A -> B -> C path.
    assert "test.tri.A -> test.tri.B" in str(ei.value)
    assert "test.tri.B -> test.tri.C" in str(ei.value)


def test_watchdog_reports_long_hold(debug_locks):
    calls = []
    ccy.set_watchdog_ms(10)
    ccy.set_watchdog_handler(
        lambda cls, held_s, stack: calls.append((cls, held_s, stack)))
    lk = ccy.Lock("test.wd.slow")
    with lk:
        time.sleep(0.05)
    assert calls, "watchdog did not fire"
    cls, held_s, stack = calls[0]
    assert cls == "test.wd.slow"
    assert held_s >= 0.01
    assert "test_concurrency.py" in stack  # the acquire site


def test_scan_long_holds_finds_wedged_holder(debug_locks):
    ccy.set_watchdog_handler(lambda *a: None)  # silence release-time report
    lk = ccy.Lock("test.wd.wedged")
    lk.acquire()
    try:
        time.sleep(0.03)
        hits = [e for e in ccy.scan_long_holds(threshold_ms=10)
                if e["lock_class"] == "test.wd.wedged"]
        assert hits
        assert hits[0]["held_s"] >= 0.01
        assert "test_concurrency.py" in hits[0]["holder_stack"]
    finally:
        lk.release()
    assert not [e for e in ccy.scan_long_holds(threshold_ms=10)
                if e["lock_class"] == "test.wd.wedged"]


def test_condition_over_wrapper_keeps_held_set_honest(debug_locks):
    cv = ccy.Condition("test.cv.C")
    with cv:
        assert ccy.held_lock_classes() == ["test.cv.C"]
        cv.wait(timeout=0.01)  # _release_save/_acquire_restore round trip
        assert ccy.held_lock_classes() == ["test.cv.C"]
    assert ccy.held_lock_classes() == []


def test_rlock_reentry_is_not_an_edge(debug_locks):
    lk = ccy.RLock("test.re.R")
    with lk:
        with lk:
            pass
    assert ccy.held_lock_classes() == []
    assert ("test.re.R", "test.re.R") not in ccy.lock_order_edges()


# ---------------------------------------------------------------------------
# ThreadRegistry + DB lifecycle
# ---------------------------------------------------------------------------


def test_registry_rejects_unnamed_thread():
    t = threading.Thread(target=lambda: None)
    with pytest.raises(ValueError, match="unnamed"):
        ccy.registry.register(t)


def test_registry_catches_and_stops_leaked_thread():
    owner = object()
    stop_ev = threading.Event()
    ccy.spawn("test-leaky", stop_ev.wait, owner=owner, stop=stop_ev.set)
    assert ccy.registry.check_leaks(owner=owner) == ["test-leaky"]
    assert ccy.registry.stop_all(owner=owner) == []
    assert ccy.registry.check_leaks(owner=owner) == []


def test_db_close_warns_on_unstopped_thread(tmp_path, monkeypatch):
    """An unstopped scrubber-style thread owned by the DB trips the
    DB.close() leak check (join timeout shortened to keep the test
    fast)."""
    orig = ccy.registry.join_all
    monkeypatch.setattr(
        ccy.registry, "join_all",
        lambda owner=None, timeout=5.0: orig(owner=owner, timeout=0.2))
    db = DB.open(str(tmp_path / "db"), Options(create_if_missing=True))
    ev = threading.Event()
    ccy.spawn("test-scrubber", ev.wait, owner=db)
    try:
        with pytest.warns(RuntimeWarning, match="leaked threads.*scrubber"):
            db.close()
    finally:
        ev.set()


def test_clean_open_write_close_leaves_no_threads(tmp_path, no_thread_leaks):
    db = DB.open(str(tmp_path / "db"), Options(create_if_missing=True))
    for i in range(100):
        db.put(b"k%03d" % i, b"v%d" % i)
    db.flush(FlushOptions())
    with warnings.catch_warnings(record=True) as caught:
        warnings.simplefilter("always")
        db.close()
    assert not [w for w in caught if "leaked threads" in str(w.message)]
    assert ccy.registry.check_leaks(owner=db) == []


def test_db_smoke_under_lock_debug(tmp_path, debug_locks):
    """A real DB open/write/read/flush/close with every lock created
    instrumented: no inversion raised, and real acquisition edges were
    recorded."""
    db = DB.open(str(tmp_path / "db"), Options(create_if_missing=True))
    try:
        for i in range(200):
            db.put(b"k%04d" % i, b"v%d" % i)
        assert db.get(b"k0000") == b"v0"
        db.flush(FlushOptions())
        assert db.get(b"k0150") == b"v150"
    finally:
        db.close()
    assert ccy.lock_order_edges(), "debug layer recorded no edges"
