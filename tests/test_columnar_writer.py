"""Byte-parity of the native columnar SST writer vs the per-entry
TableBuilder path — including multi-output cutting (the rule from reference
CompactionOutputs::ShouldStopBefore: cut only at user-key boundaries once the
file passes max_output_file_size). Pure host test: no JAX involved."""

import types

import numpy as np
import pytest

from toplingdb_tpu import native
from toplingdb_tpu.compaction.compaction_job import CompactionStats, build_outputs
from toplingdb_tpu.db import dbformat
from toplingdb_tpu.db.dbformat import InternalKeyComparator, ValueType
from toplingdb_tpu.ops.columnar_io import ColumnarKV, write_tables_columnar
from toplingdb_tpu.table.builder import TableOptions

pytestmark = pytest.mark.skipif(
    native.lib() is None, reason="native library unavailable"
)


def make_kv(entries):
    """Build a ColumnarKV + (vtypes, seqs) from sorted (ikey, value) pairs."""
    key_buf = bytearray()
    val_buf = bytearray()
    ko, kl, vo, vl, vts, sqs = [], [], [], [], [], []
    for ik, v in entries:
        ko.append(len(key_buf))
        kl.append(len(ik))
        key_buf += ik
        vo.append(len(val_buf))
        vl.append(len(v))
        val_buf += v
        vts.append(ik[-8])
        sqs.append(dbformat.extract_seqno(ik))
    return (
        ColumnarKV(
            np.frombuffer(bytes(key_buf), dtype=np.uint8),
            np.array(ko, np.int32), np.array(kl, np.int32),
            np.frombuffer(bytes(val_buf), dtype=np.uint8),
            np.array(vo, np.int32), np.array(vl, np.int32),
        ),
        np.array(vts, np.int64),
        np.array(sqs, np.uint64),
    )


def run_both(mem_env, entries, max_size, opts=None):
    opts = opts or TableOptions()
    icmp = InternalKeyComparator(dbformat.BYTEWISE)
    mem_env.create_dir("/ref")
    mem_env.create_dir("/col")

    counters = {"ref": 100, "col": 100}

    def alloc(which):
        counters[which] += 1
        return counters[which]

    comp = types.SimpleNamespace(max_output_file_size=max_size)
    stats = CompactionStats()
    ref_metas = build_outputs(
        mem_env, "/ref", icmp, comp, iter(entries), [],
        lambda: alloc("ref"), opts, stats, creation_time=7,
    )

    kv, vts, sqs = make_kv(entries)
    files = write_tables_columnar(
        mem_env, "/col", lambda: alloc("col"), icmp, opts, kv,
        np.arange(kv.n, dtype=np.int32),
        np.full(kv.n, -1, dtype=np.int64), vts, sqs, [], 7,
        max_output_file_size=max_size,
    )
    return ref_metas, files, mem_env


def test_single_output_byte_parity(mem_env):
    entries = [
        (dbformat.make_internal_key(f"key{i:05d}".encode(), 1000 + i,
                                    ValueType.VALUE),
         f"value-{i}".encode() * 3)
        for i in range(500)
    ]
    ref, col, env = run_both(mem_env, entries, max_size=2 ** 62)
    assert len(ref) == 1 and len(col) == 1
    assert env.read_file(f"/ref/{ref[0].number:06d}.sst") == \
        env.read_file(col[0][1])


def test_multi_output_cutting_byte_parity(mem_env):
    entries = [
        (dbformat.make_internal_key(f"key{i:05d}".encode(), 1000 + i,
                                    ValueType.VALUE),
         f"value-{i}".encode() * 8)
        for i in range(3000)
    ]
    ref, col, env = run_both(mem_env, entries, max_size=16 * 1024)
    assert len(ref) > 1, "test must actually exercise cutting"
    assert len(ref) == len(col)
    for m, f in zip(ref, col):
        assert env.read_file(f"/ref/{m.number:06d}.sst") == \
            env.read_file(f[1]), f"file {m.number} differs"
        assert f[2].num_entries == m.num_entries


def test_cut_never_splits_a_user_key(mem_env):
    """Duplicate user keys spanning the size boundary stay in one file on
    both paths."""
    entries = []
    for i in range(400):
        uk = f"key{i // 8:05d}".encode()  # 8 versions per user key
        entries.append(
            (dbformat.make_internal_key(uk, 5000 - i, ValueType.VALUE),
             f"v{i}".encode() * 40)
        )
    ref, col, env = run_both(mem_env, entries, max_size=4 * 1024)
    assert len(ref) == len(col) and len(ref) > 1
    seen = set()
    for m, f in zip(ref, col):
        assert env.read_file(f"/ref/{m.number:06d}.sst") == \
            env.read_file(f[1])
        first_uk = dbformat.extract_user_key(m.smallest)
        assert first_uk not in seen, "user key split across outputs"
        seen.add(dbformat.extract_user_key(m.largest))


def test_columnar_writer_compressed_byte_parity(tmp_path):
    """Snappy/zstd outputs through the NATIVE compressed section builder
    must byte-match TableBuilder fed the same stream (the per-block Python
    compress path)."""
    import random

    import numpy as np
    import pytest

    from toplingdb_tpu.db.dbformat import (
        InternalKeyComparator, ValueType, make_internal_key,
    )
    from toplingdb_tpu.env import default_env
    from toplingdb_tpu.ops.columnar_io import ColumnarKV, write_tables_columnar
    from toplingdb_tpu.table import format as fmt
    from toplingdb_tpu.table.builder import TableBuilder, TableOptions
    from toplingdb_tpu.utils import codecs

    icmp = InternalKeyComparator()
    env = default_env()
    rng = random.Random(11)
    entries = []
    for i in range(4000):
        k = make_internal_key(b"key%06d" % i, i + 1, ValueType.VALUE)
        v = (b"common-prefix-" * 2) + bytes(
            rng.randrange(97, 105) for _ in range(rng.randrange(4, 60)))
        entries.append((k, v))
    if not (codecs.available("snappy") or codecs.available("zstd")):
        pytest.skip("no native codecs installed")
    for codec, name in ((fmt.SNAPPY_COMPRESSION, "snappy"),
                        (fmt.ZSTD_COMPRESSION, "zstd")):
        if not codecs.available(name):
            continue
        topts = TableOptions(block_size=1024, compression=codec)
        ref = str(tmp_path / f"ref_{name}.sst")
        w = env.new_writable_file(ref)
        b = TableBuilder(w, icmp, topts, creation_time=3,
                         column_family_name="default")
        for k, v in entries:
            b.add(k, v)
        b.finish()
        w.close()

        kbuf = bytearray()
        vbuf = bytearray()
        ko, kl, vo, vl = [], [], [], []
        for k, v in entries:
            ko.append(len(kbuf)); kl.append(len(k)); kbuf += k
            vo.append(len(vbuf)); vl.append(len(v)); vbuf += v
        kv = ColumnarKV(
            np.frombuffer(bytes(kbuf), np.uint8), np.array(ko, np.int32),
            np.array(kl, np.int32),
            np.frombuffer(bytes(vbuf), np.uint8), np.array(vo, np.int32),
            np.array(vl, np.int32))
        n = len(entries)
        cnt = [700]

        def alloc():
            cnt[0] += 1
            return cnt[0]

        files = write_tables_columnar(
            env, str(tmp_path), alloc, icmp, topts, kv,
            np.arange(n, dtype=np.int32), np.full(n, -1, np.int64),
            np.full(n, int(ValueType.VALUE), np.int32),
            np.arange(1, n + 1, dtype=np.uint64), [], creation_time=3)
        got = open(files[0][1], "rb").read()
        want = open(ref, "rb").read()
        assert got == want, f"{name}: native compressed section diverges"


@pytest.mark.parametrize("compression", ["none", "snappy"])
def test_scan_refvals_parity(tmp_path, compression):
    """tpulsm_scan_blocks_refvals (values referenced into the file image)
    returns exactly the entries of the value-copying scan. Compressed
    files must transparently take the copying path (refvals returns -5)."""
    from toplingdb_tpu.env import default_env
    from toplingdb_tpu.ops.columnar_io import scan_table_columnar
    from toplingdb_tpu.table import format as fmt
    from toplingdb_tpu.table.builder import TableBuilder
    from toplingdb_tpu.table.factory import open_table
    from toplingdb_tpu.utils import codecs

    if compression == "snappy" and not codecs.available("snappy"):
        pytest.skip("snappy unavailable")
    env = default_env()
    icmp = InternalKeyComparator(dbformat.BYTEWISE)
    topts = TableOptions(
        block_size=512,
        compression=(fmt.SNAPPY_COMPRESSION if compression == "snappy"
                     else fmt.NO_COMPRESSION))
    path = str(tmp_path / f"refvals_{compression}.sst")
    w = env.new_writable_file(path)
    b = TableBuilder(w, icmp, topts)
    for i in range(4000):
        ik = dbformat.make_internal_key(
            b"key%06d" % i, 1000 + i, ValueType.VALUE)
        b.add(ik, b"value-%06d" % (i * 13))
    b.finish()
    w.close()

    r = open_table(env.new_random_access_file(path), icmp, topts)
    kv_ref = scan_table_columnar(r, ref_values=True)
    kv_cp = scan_table_columnar(r, ref_values=False)
    assert kv_ref.n == kv_cp.n == 4000
    assert kv_ref.to_entries() == kv_cp.to_entries()
    if compression == "none" and hasattr(
            native.lib(), "tpulsm_scan_blocks_refvals"):
        # The refvals path actually engaged: val_buf IS the file image.
        assert len(kv_ref.val_buf) == env.get_file_size(path)
        assert len(kv_cp.val_buf) < len(kv_ref.val_buf)
