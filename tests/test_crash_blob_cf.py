"""Kill -9 crash soak over column families + blob files + blob GC
(promoted from session soak testing; complements tools/db_stress's default-
CF crash loop). A child process does synced writes, journaling each op
AFTER its DB write returns — so every journaled op must survive the kill;
only the single in-flight op (db-committed, not yet journaled) may
diverge."""

import os
import random
import shutil
import subprocess
import sys
import time

_CHILD = r"""
import os, random, sys
sys.path.insert(0, %(repo)r)
from toplingdb_tpu.db.db import DB
from toplingdb_tpu.options import Options, WriteOptions

d, journal, seed = sys.argv[1], sys.argv[2], int(sys.argv[3])
rng = random.Random(seed)
o = Options(write_buffer_size=8 * 1024, enable_blob_files=True,
            min_blob_size=64, enable_blob_garbage_collection=True,
            blob_garbage_collection_age_cutoff=0.5,
            level0_file_num_compaction_trigger=3)
db = DB.open(d, o)
cf = db.get_column_family("meta") or db.create_column_family("meta")
jf = open(journal, "a", buffering=1)
wo = WriteOptions(sync=True)
i = 0
while True:
    k = b"key%%05d" %% rng.randrange(1500)
    v = (b"B%%05d" %% i) * (20 if rng.random() < 0.3 else 1)
    use_cf = rng.random() < 0.25
    if rng.random() < 0.85:
        db.put(k, v, wo, cf=cf if use_cf else None)
        jf.write("P %%d %%s %%s\n" %% (int(use_cf), k.decode(), v.decode()))
    else:
        db.delete(k, wo, cf=cf if use_cf else None)
        jf.write("D %%d %%s\n" %% (int(use_cf), k.decode()))
    jf.flush(); os.fsync(jf.fileno())
    i += 1
"""


def test_crash_recovery_with_cfs_and_blobs(tmp_path):
    from toplingdb_tpu.db.db import DB
    from toplingdb_tpu.options import Options

    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    child_py = str(tmp_path / "child.py")
    open(child_py, "w").write(_CHILD % {"repo": repo})
    base = str(tmp_path / "db")
    journal = str(tmp_path / "journal")
    rng = random.Random(99)
    verified_any = False
    for rnd in range(3):
        p = subprocess.Popen(
            [sys.executable, child_py, base, journal, str(rnd)],
            stdout=subprocess.DEVNULL, stderr=subprocess.PIPE,
        )
        time.sleep(rng.uniform(1.5, 3.0))
        alive = p.poll() is None
        if not alive:
            # Child crashed on its own: that's a bug, not a kill.
            raise AssertionError(
                f"round {rnd}: child died early: "
                f"{p.stderr.read().decode()[-800:]}"
            )
        p.kill()
        p.wait()
        if not os.path.exists(journal):
            continue  # killed before the first op completed
        verified_any = True
        model = [{}, {}]
        for line in open(journal):
            parts = line.rstrip("\n").split(" ", 3)
            if parts[0] == "P":
                model[int(parts[1])][parts[2].encode()] = parts[3].encode()
            else:
                model[int(parts[1])].pop(parts[2].encode(), None)
        o = Options(enable_blob_files=True, min_blob_size=64,
                    enable_blob_garbage_collection=True,
                    blob_garbage_collection_age_cutoff=0.5)
        db = DB.open(base, o)
        cfh = db.get_column_family("meta")
        bad = 0
        for which, m in enumerate(model):
            h = cfh if which else None
            for k, v in m.items():
                if db.get(k, cf=h) != v:
                    bad += 1
        # One legitimate in-flight divergence can accrue PER KILL (the op
        # whose db-write committed but whose journal line didn't), and they
        # persist across rounds unless overwritten.
        assert bad <= rnd + 1, f"round {rnd}: {bad} losses (> {rnd + 1})"
        db.verify_checksum()
        db.close()
    assert verified_any, "no round ever verified anything"
    shutil.rmtree(base, ignore_errors=True)
