"""CompactionIterator state-machine corpus, shaped after the reference's
db/compaction/compaction_iterator_test.cc (/root/reference): the long tail
of NextFromInput — snapshot boundary edges, SingleDelete interleavings,
merge folding across stripes, range-tombstone shadowing, compaction-filter
x snapshot interactions, seqno zeroing.

Every case runs through BOTH engines:
  * the CPU CompactionIterator (the reference state machine), asserted
    against an explicit expected survivor list, and
  * the device data plane (device_gc_entries — sort + GC mask + host
    complex-group resolution), asserted EQUAL to the CPU output,
so each case is simultaneously a semantics test and a CPU/device parity
test (VERDICT r03 item 8)."""

from __future__ import annotations

import random

import pytest

from toplingdb_tpu.compaction.compaction_iterator import CompactionIterator
from toplingdb_tpu.db.dbformat import (
    InternalKeyComparator,
    ValueType as VT,
    make_internal_key,
    split_internal_key,
)
from toplingdb_tpu.db.range_del import RangeDelAggregator, RangeTombstone
from toplingdb_tpu.ops.device_compaction import device_gc_entries
from toplingdb_tpu.utils.compaction_filter import CompactionFilter, Decision
from toplingdb_tpu.utils.merge_operator import (
    StringAppendOperator,
    UInt64AddOperator,
)

ICMP = InternalKeyComparator()


class _W:
    __slots__ = ("k",)

    def __init__(self, k):
        self.k = k

    def __lt__(self, other):
        return ICMP.compare(self.k, other.k) < 0


class FakeIter:
    def __init__(self, items):
        self._items = sorted(items, key=lambda kv: _W(kv[0]))
        self._i = 0

    def valid(self):
        return self._i < len(self._items)

    def key(self):
        return self._items[self._i][0]

    def value(self):
        return self._items[self._i][1]

    def next(self):
        self._i += 1

    def seek_to_first(self):
        self._i = 0


def u64(x):
    return x.to_bytes(8, "little")


class DropShortFilter(CompactionFilter):
    """Removes values shorter than 3 bytes."""

    def name(self):
        return "drop-short"

    def filter(self, level, key, value):
        if len(value) < 3:
            return Decision.REMOVE, None
        return Decision.KEEP, None


class UpperFilter(CompactionFilter):
    def name(self):
        return "upper"

    def filter(self, level, key, value):
        return Decision.CHANGE_VALUE, value.upper()


def _rd(tombstones):
    if not tombstones:
        return None
    rd = RangeDelAggregator(ICMP.user_comparator)
    for seq, b, e in tombstones:
        rd.add(RangeTombstone(seq, b, e))
    return rd


def run_cpu(entries, snapshots, bottommost, merge_op, cfilter, tombstones):
    items = [(make_internal_key(k, s, t), v) for k, s, t, v in entries]
    ci = CompactionIterator(
        FakeIter(items), ICMP, list(snapshots),
        bottommost_level=bottommost, merge_operator=merge_op,
        compaction_filter=cfilter, range_del_agg=_rd(tombstones),
    )
    return [(*split_internal_key(ik), v) for ik, v in ci.entries()]


def run_device(entries, snapshots, bottommost, merge_op, cfilter,
               tombstones):
    items = [(make_internal_key(k, s, t), v) for k, s, t, v in entries]
    stream = device_gc_entries(
        items, ICMP, list(snapshots), bottommost,
        merge_operator=merge_op, compaction_filter=cfilter,
        rd=_rd(tombstones),
    )
    return [(*split_internal_key(ik), v) for ik, v in stream]


V, D, SD, M = VT.VALUE, VT.DELETION, VT.SINGLE_DELETION, VT.MERGE

# (name, entries[(uk, seq, type, value)], snapshots, bottommost,
#  merge_op|None, cfilter|None, tombstones[(seq, begin, end)],
#  expected survivors [(uk, seq, type, value)] or None = parity-only)
CASES = [
    # --- A. overwrite / visibility --------------------------------------
    ("overwrite_newest_wins",
     [(b"a", 5, V, b"v5"), (b"a", 3, V, b"v3")], (), False, None, None, (),
     [(b"a", 5, V, b"v5")]),
    ("distinct_keys_all_survive",
     [(b"a", 5, V, b"va"), (b"b", 4, V, b"vb"), (b"c", 3, V, b"vc")],
     (), False, None, None, (),
     [(b"a", 5, V, b"va"), (b"b", 4, V, b"vb"), (b"c", 3, V, b"vc")]),
    ("snapshot_on_exact_seq_boundary",
     # seq == snapshot is VISIBLE to it: v5 is snapshot 5's version, so
     # v4 (same stripe, older) drops; v6 newer than the snapshot.
     [(b"a", 6, V, b"v6"), (b"a", 5, V, b"v5"), (b"a", 4, V, b"v4")],
     (5,), False, None, None, (),
     [(b"a", 6, V, b"v6"), (b"a", 5, V, b"v5")]),
    ("adjacent_snapshots_each_pin_a_version",
     [(b"a", 9, V, b"v9"), (b"a", 8, V, b"v8"), (b"a", 7, V, b"v7")],
     (7, 8), False, None, None, (),
     [(b"a", 9, V, b"v9"), (b"a", 8, V, b"v8"), (b"a", 7, V, b"v7")]),
    ("duplicate_snapshots_collapse",
     [(b"a", 9, V, b"v9"), (b"a", 5, V, b"v5"), (b"a", 3, V, b"v3")],
     (4, 4), False, None, None, (),
     [(b"a", 9, V, b"v9"), (b"a", 3, V, b"v3")]),
    ("snapshot_above_everything",
     [(b"a", 5, V, b"v5"), (b"a", 3, V, b"v3")], (100,), False, None, None,
     (), [(b"a", 5, V, b"v5")]),
    ("snapshot_below_everything",
     [(b"a", 5, V, b"v5"), (b"a", 3, V, b"v3")], (1,), False, None, None,
     (), [(b"a", 5, V, b"v5")]),
    ("empty_user_key",
     [(b"", 5, V, b"v5"), (b"", 3, V, b"v3"), (b"a", 4, V, b"va")],
     (), False, None, None, (),
     [(b"", 5, V, b"v5"), (b"a", 4, V, b"va")]),

    # --- B. point deletions ---------------------------------------------
    ("delete_shadows_put_nonbottom",
     [(b"a", 5, D, b""), (b"a", 3, V, b"v3")], (), False, None, None, (),
     [(b"a", 5, D, b"")]),
    ("delete_dropped_at_bottommost",
     [(b"a", 5, D, b""), (b"a", 3, V, b"v3")], (), True, None, None, (),
     []),
    ("lone_delete_bottommost_drops",
     [(b"a", 5, D, b"")], (), True, None, None, (), []),
    ("lone_delete_nonbottom_travels",
     [(b"a", 5, D, b"")], (), False, None, None, (), [(b"a", 5, D, b"")]),
    ("delete_kept_when_snapshot_pins_old_value",
     [(b"a", 5, D, b""), (b"a", 3, V, b"v3")], (4,), True, None, None, (),
     [(b"a", 5, D, b""), (b"a", 0, V, b"v3")]),
    ("delete_then_newer_put",
     [(b"a", 7, V, b"v7"), (b"a", 5, D, b""), (b"a", 3, V, b"v3")],
     (), True, None, None, (), [(b"a", 0, V, b"v7")]),
    ("two_deletes_stack",
     [(b"a", 7, D, b""), (b"a", 5, D, b""), (b"a", 3, V, b"v3")],
     (), False, None, None, (), [(b"a", 7, D, b"")]),
    ("delete_per_stripe_survives",
     [(b"a", 9, D, b""), (b"a", 7, D, b""), (b"a", 5, V, b"v5")],
     (8,), False, None, None, (),
     [(b"a", 9, D, b""), (b"a", 7, D, b"")]),

    # --- C. single deletes ----------------------------------------------
    ("sd_annihilates_matching_put",
     [(b"a", 9, SD, b""), (b"a", 7, V, b"v7")], (), False, None, None, (),
     []),
    ("sd_across_snapshot_keeps_both",
     [(b"a", 9, SD, b""), (b"a", 7, V, b"v7")], (8,), False, None, None,
     (), [(b"a", 9, SD, b""), (b"a", 7, V, b"v7")]),
    ("sd_unmatched_travels_nonbottom",
     [(b"a", 9, SD, b"")], (), False, None, None, (),
     [(b"a", 9, SD, b"")]),
    ("sd_unmatched_drops_bottommost",
     [(b"a", 9, SD, b"")], (), True, None, None, (), []),
    ("sd_sees_only_newest_put",
     # our semantics: the whole annihilated group is invisible to readers
     # at or above the SD, and no snapshot pins the older puts -> nothing
     # survives (read-consistent: every live reader sees NotFound).
     [(b"a", 9, SD, b""), (b"a", 7, V, b"v7"), (b"a", 5, V, b"v5")],
     (), False, None, None, (), []),
    ("sd_snapshot_protects_oldest",
     # SD(9)+PUT(7) are in the same stripe (both above snapshot 6) and
     # annihilate; the snapshot pins v5 (the reference's
     # SingleDeleteAcrossSnapshot shape keeps only the protected stripe).
     [(b"a", 9, SD, b""), (b"a", 7, V, b"v7"), (b"a", 5, V, b"v5")],
     (6,), False, None, None, (),
     [(b"a", 5, V, b"v5")]),
    ("sd_meets_delete_keeps_sd",
     [(b"a", 9, SD, b""), (b"a", 7, D, b"")], (), False, None, None, (),
     [(b"a", 9, SD, b"")]),
    ("two_sds_collapse",
     [(b"a", 9, SD, b""), (b"a", 8, SD, b""), (b"a", 7, V, b"v")],
     (), False, None, None, (), [(b"a", 9, SD, b"")]),
    ("sd_only_touches_its_key",
     [(b"a", 9, SD, b""), (b"a", 7, V, b"va"), (b"b", 8, V, b"vb")],
     (), False, None, None, (), [(b"b", 8, V, b"vb")]),

    # --- D. merges -------------------------------------------------------
    ("merge_folds_onto_base",
     [(b"c", 9, M, u64(1)), (b"c", 7, M, u64(2)), (b"c", 5, V, u64(10))],
     (), False, UInt64AddOperator, None, (),
     [(b"c", 9, V, u64(13))]),
    ("merge_over_delete_restarts",
     [(b"c", 9, M, u64(5)), (b"c", 7, D, b""), (b"c", 5, V, u64(10))],
     (), False, UInt64AddOperator, None, (),
     [(b"c", 9, V, u64(5))]),
    ("merge_without_base_travels_nonbottom",
     [(b"c", 9, M, u64(5)), (b"c", 7, M, u64(3))],
     (), False, UInt64AddOperator, None, (),
     [(b"c", 9, M, u64(8))]),
    ("merge_without_base_finalizes_bottommost",
     [(b"c", 9, M, u64(5)), (b"c", 7, M, u64(3))],
     (), True, UInt64AddOperator, None, (),
     [(b"c", 0, V, u64(8))]),
    ("merge_stripes_fold_independently",
     [(b"c", 9, M, u64(1)), (b"c", 7, M, u64(2)), (b"c", 5, M, u64(4))],
     (8, 6), False, UInt64AddOperator, None, (),
     [(b"c", 9, M, u64(1)), (b"c", 7, M, u64(2)), (b"c", 5, M, u64(4))]),
    ("merge_snapshot_splits_chain",
     [(b"c", 9, M, u64(1)), (b"c", 7, M, u64(2)), (b"c", 5, V, u64(10))],
     (8,), False, UInt64AddOperator, None, (),
     [(b"c", 9, M, u64(1)), (b"c", 7, V, u64(12))]),
    ("string_append_order",
     [(b"s", 9, M, b"c"), (b"s", 7, M, b"b"), (b"s", 5, V, b"a")],
     (), False, StringAppendOperator, None, (),
     [(b"s", 9, V, b"a,b,c")]),
    ("merge_after_sd_pair",
     # SD(9)+PUT(7) annihilate; merge(5) folds in its own stripe below.
     [(b"m", 9, SD, b""), (b"m", 7, V, b"x"), (b"m", 5, M, b"q")],
     (), True, StringAppendOperator, None, (), None),
    ("merge_base_under_snapshot",
     # MergeUntil stops at the stripe boundary (reference
     # merge_helper.cc): the operand cannot consume a base another
     # snapshot still sees — it travels unfolded.
     [(b"c", 9, M, u64(1)), (b"c", 5, V, u64(10))],
     (6,), False, UInt64AddOperator, None, (),
     [(b"c", 9, M, u64(1)), (b"c", 5, V, u64(10))]),
    ("merge_two_keys_interleaved",
     [(b"a", 9, M, u64(1)), (b"a", 5, V, u64(2)),
      (b"b", 8, M, u64(3)), (b"b", 4, V, u64(4))],
     (), False, UInt64AddOperator, None, (),
     [(b"a", 9, V, u64(3)), (b"b", 8, V, u64(7))]),
    # --- D2. SingleDelete x Merge interleavings (VERDICT r03 item 8's
    # explicitly named long-tail family) ------------------------------
    ("sd_over_merge_chain_consumes_it",
     # The SD shadows the merge chain below it; the SD itself travels
     # (reads at/above it correctly see NotFound).
     [(b"a", 9, SD, b""), (b"a", 7, M, u64(3)), (b"a", 5, V, u64(10))],
     (), False, UInt64AddOperator, None, (),
     [(b"a", 9, SD, b"")]),
    ("sd_over_merge_chain_bottommost",
     [(b"a", 9, SD, b""), (b"a", 7, M, u64(3)), (b"a", 5, V, u64(10))],
     (), True, UInt64AddOperator, None, (),
     [(b"a", 9, SD, b"")]),
    ("merge_over_sd_restarts_chain",
     # Like merge-over-DELETE: the SD terminates the operand scan, so the
     # top merge folds with no base.
     [(b"a", 9, M, u64(3)), (b"a", 7, SD, b""), (b"a", 5, V, u64(10))],
     (), False, UInt64AddOperator, None, (),
     [(b"a", 9, V, u64(3))]),
    ("merge_over_sd_bottommost_zeroes",
     [(b"a", 9, M, u64(3)), (b"a", 7, SD, b""), (b"a", 5, V, u64(10))],
     (), True, UInt64AddOperator, None, (),
     [(b"a", 0, V, u64(3))]),
    ("sd_splits_merge_chain",
     [(b"a", 9, M, u64(1)), (b"a", 8, SD, b""), (b"a", 7, M, u64(2)),
      (b"a", 5, V, u64(4))],
     (), True, UInt64AddOperator, None, (),
     [(b"a", 0, V, u64(1))]),
    ("delete_under_merge_bottommost",
     [(b"a", 9, M, u64(5)), (b"a", 7, D, b""), (b"a", 5, V, u64(9))],
     (), True, UInt64AddOperator, None, (),
     [(b"a", 0, V, u64(5))]),
    ("merge_chain_split_by_snapshot_bottommost",
     # Stripe boundary: the newer operand stays an unfolded MERGE; the
     # older finalizes and zeroes at the bottom.
     [(b"a", 9, M, u64(1)), (b"a", 5, M, u64(2))],
     (7,), True, UInt64AddOperator, None, (),
     [(b"a", 9, M, u64(1)), (b"a", 0, V, u64(2))]),

    # --- E. range tombstones --------------------------------------------
    ("range_del_covers_older",
     [(b"b", 3, V, b"v3"), (b"x", 4, V, b"vx")],
     (), False, None, None, ((5, b"a", b"c"),),
     [(b"x", 4, V, b"vx")]),
    ("range_del_does_not_cover_newer",
     [(b"b", 7, V, b"v7")], (), False, None, None, ((5, b"a", b"c"),),
     [(b"b", 7, V, b"v7")]),
    ("range_del_end_exclusive",
     [(b"c", 3, V, b"vc")], (), False, None, None, ((5, b"a", b"c"),),
     [(b"c", 3, V, b"vc")]),
    ("range_del_begin_inclusive",
     [(b"a", 3, V, b"va")], (), False, None, None, ((5, b"a", b"c"),),
     []),
    ("range_del_cross_stripe_no_shadow",
     # tombstone seq 7 is above snapshot 4; entry seq 3 is in the older
     # stripe: the tombstone cannot drop it (snapshot reader at 4 must
     # still see v3).
     [(b"b", 3, V, b"v3")], (4,), False, None, None, ((7, b"a", b"c"),),
     [(b"b", 3, V, b"v3")]),
    ("range_del_same_stripe_shadows",
     [(b"b", 3, V, b"v3")], (9,), False, None, None, ((7, b"a", b"c"),),
     []),
    ("range_del_over_delete",
     [(b"b", 3, D, b"")], (), False, None, None, ((7, b"a", b"c"),), None),
    ("range_del_over_merge_chain",
     [(b"b", 6, M, u64(1)), (b"b", 3, V, u64(5))],
     (), False, UInt64AddOperator, None, ((7, b"a", b"c"),), None),

    # --- F. compaction filter x snapshots -------------------------------
    ("filter_removes_unprotected",
     [(b"a", 5, V, b"x"), (b"b", 4, V, b"keepme")],
     (), False, None, DropShortFilter, (),
     [(b"b", 4, V, b"keepme")]),
    ("filter_skips_snapshot_protected",
     # seq 5 > earliest snapshot 3: the filter must not run on it; seq 2
     # is at/below the earliest snapshot, so the filter DOES run there
     # (the reference's documented snapshot-vs-filter semantics) and
     # removes the short value.
     [(b"a", 5, V, b"x"), (b"a", 2, V, b"y")],
     (3,), False, None, DropShortFilter, (),
     [(b"a", 5, V, b"x")]),
    ("filter_changes_value",
     [(b"a", 5, V, b"abc")], (), False, None, UpperFilter, (),
     [(b"a", 5, V, b"ABC")]),
    ("filter_never_sees_deletes",
     [(b"a", 5, D, b""), (b"b", 4, V, b"xy")],
     (), False, None, DropShortFilter, (),
     [(b"a", 5, D, b"")]),
    ("filter_and_bottommost_zeroing",
     [(b"a", 5, V, b"long-enough"), (b"b", 4, V, b"x")],
     (), True, None, DropShortFilter, (),
     [(b"a", 0, V, b"long-enough")]),

    # --- G. seqno zeroing / misc edges ----------------------------------
    ("zeroing_only_bottommost",
     [(b"a", 5, V, b"v")], (), False, None, None, (),
     [(b"a", 5, V, b"v")]),
    ("zeroing_at_bottommost",
     [(b"a", 5, V, b"v")], (), True, None, None, (), [(b"a", 0, V, b"v")]),
    ("zeroing_respects_snapshots",
     [(b"a", 5, V, b"v")], (3,), True, None, None, (),
     [(b"a", 5, V, b"v")]),
    ("already_zero_seq_survives",
     [(b"a", 0, V, b"v")], (), True, None, None, (), [(b"a", 0, V, b"v")]),
    ("mixed_keys_long_and_short",
     [(b"aa", 5, V, b"1"), (b"aaa", 4, V, b"2"), (b"a", 3, V, b"3")],
     (), False, None, None, (),
     [(b"a", 3, V, b"3"), (b"aa", 5, V, b"1"), (b"aaa", 4, V, b"2")]),
    ("prefix_keys_are_distinct",
     [(b"ab", 9, V, b"x"), (b"ab", 7, V, b"y"), (b"abc", 8, V, b"z")],
     (), False, None, None, (),
     [(b"ab", 9, V, b"x"), (b"abc", 8, V, b"z")]),
]


@pytest.mark.parametrize(
    "name,entries,snaps,bottom,mop,cf,tombs,expected",
    CASES, ids=[c[0] for c in CASES])
def test_corpus_cpu_semantics_and_device_parity(
        name, entries, snaps, bottom, mop, cf, tombs, expected):
    mo = mop() if mop else None
    cfi = cf() if cf else None
    got = run_cpu(entries, snaps, bottom, mo, cfi, tombs)
    if expected is not None:
        assert got == expected, f"{name}: CPU semantics"
    dev = run_device(entries, snaps, bottom, mo, cfi, tombs)
    assert dev == got, f"{name}: device != cpu"


@pytest.mark.parametrize("seed", range(8))
def test_randomized_cpu_device_equivalence(seed):
    """Random op soup over a small keyspace: the device plane must equal
    the CPU state machine entry-for-entry (values, types, zeroed seqs)."""
    rng = random.Random(seed)
    keys = [b"k%02d" % i for i in range(12)]
    entries = []
    seq = 1
    for _ in range(300):
        k = rng.choice(keys)
        r = rng.random()
        if r < 0.55:
            entries.append((k, seq, V, b"val%d" % seq))
        elif r < 0.75:
            entries.append((k, seq, D, b""))
        else:
            entries.append((k, seq, M, u64(rng.randrange(100))))
        seq += 1
    snaps = sorted(rng.sample(range(1, seq), rng.randrange(0, 4)))
    bottom = bool(seed % 2)
    tombs = []
    if seed % 3 == 0:
        a, b = sorted(rng.sample(keys, 2))
        tombs.append((rng.randrange(1, seq), a, b))
    mo = UInt64AddOperator()
    cpu = run_cpu(entries, snaps, bottom, mo, None, tombs)
    dev = run_device(entries, snaps, bottom, mo, None, tombs)
    assert dev == cpu
