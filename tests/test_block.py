import random

import pytest

from toplingdb_tpu.table.block import BlockBuilder, BlockIter


def bytewise(a, b):
    return (a > b) - (a < b)


def build(entries, restart_interval=4):
    b = BlockBuilder(restart_interval=restart_interval)
    for k, v in entries:
        b.add(k, v)
    return b.finish()


def test_roundtrip_sequential():
    entries = [(f"key{i:05d}".encode(), f"val{i}".encode()) for i in range(100)]
    data = build(entries)
    it = BlockIter(data, bytewise)
    it.seek_to_first()
    assert list(it.entries()) == entries


def test_prefix_compression_shrinks():
    entries = [(f"commonprefix{i:05d}".encode(), b"v") for i in range(64)]
    data = build(entries, restart_interval=16)
    raw = sum(len(k) + len(v) for k, v in entries)
    assert len(data) < raw  # shared prefixes elided


def test_seek():
    entries = [(f"k{i:04d}".encode(), str(i).encode()) for i in range(0, 200, 2)]
    data = build(entries)
    it = BlockIter(data, bytewise)
    # Exact hit.
    it.seek(b"k0100")
    assert it.valid() and it.key() == b"k0100"
    # Between keys: lands on next.
    it.seek(b"k0101")
    assert it.valid() and it.key() == b"k0102"
    # Before first.
    it.seek(b"")
    assert it.valid() and it.key() == b"k0000"
    # After last.
    it.seek(b"k9999")
    assert not it.valid()


def test_seek_for_prev():
    entries = [(f"k{i:04d}".encode(), b"v") for i in range(0, 100, 10)]
    it = BlockIter(build(entries), bytewise)
    it.seek_for_prev(b"k0055")
    assert it.valid() and it.key() == b"k0050"
    it.seek_for_prev(b"k0050")
    assert it.valid() and it.key() == b"k0050"
    it.seek_for_prev(b"k")
    assert not it.valid()


def test_prev_walk():
    entries = [(f"k{i:03d}".encode(), str(i).encode()) for i in range(37)]
    it = BlockIter(build(entries, restart_interval=5), bytewise)
    it.seek_to_last()
    got = []
    while it.valid():
        got.append((it.key(), it.value()))
        it.prev()
    assert got == list(reversed(entries))


def test_random_seeks_match_sorted_list():
    rng = random.Random(7)
    keys = sorted({rng.randbytes(rng.randint(1, 12)) for _ in range(300)})
    entries = [(k, k[::-1]) for k in keys]
    it = BlockIter(build(entries, restart_interval=7), bytewise)
    for _ in range(200):
        t = rng.randbytes(rng.randint(1, 12))
        it.seek(t)
        expect = next((k for k in keys if k >= t), None)
        if expect is None:
            assert not it.valid()
        else:
            assert it.valid() and it.key() == expect


def test_empty_block():
    data = BlockBuilder().finish()
    it = BlockIter(data, bytewise)
    it.seek_to_first()
    assert not it.valid()
    it.seek(b"x")
    assert not it.valid()
    it.seek_to_last()
    assert not it.valid()
