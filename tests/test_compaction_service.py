"""Upstream CompactionService path: DB::OpenAndCompact analogue + the
DB-side executor (reference db/compaction/compaction_service_test.cc)."""

import json
import os

import pytest

from toplingdb_tpu.compaction.compaction_service import (
    CompactionServiceExecutorFactory,
    CompactionServiceInput,
    CompactionServiceResult,
    InProcessCompactionService,
    SubprocessCompactionService,
    open_and_compact,
)
from toplingdb_tpu.db.db import DB
from toplingdb_tpu.options import Options


def _fill_db(path, n=3000, overwrite=2000):
    o = Options(write_buffer_size=1 << 14, disable_auto_compactions=True)
    db = DB.open(path, o)
    for i in range(n):
        db.put(b"key%05d" % (i % overwrite), b"val%06d" % i)
    db.delete(b"key00007")
    db.flush()
    return db


def test_open_and_compact_worker_side(tmp_path):
    dbp = str(tmp_path / "db")
    outp = str(tmp_path / "out")
    db = _fill_db(dbp)
    version = db.versions.cf_current(0)
    nums = [f.number for f in version.files[0]]
    assert len(nums) >= 2
    db.close()

    inp = CompactionServiceInput(
        cf_name="default", input_files=nums, output_level=2,
        bottommost=True, snapshots=[], max_output_file_size=1 << 62,
    )
    res = CompactionServiceResult.from_json(
        open_and_compact(dbp, outp, inp.to_json())
    )
    assert res.status == "ok", res.status
    assert res.output_files and res.bytes_written > 0
    # Outputs exist in output_dir only; the source DB dir is untouched.
    for d in res.output_files:
        assert os.path.exists(os.path.join(outp, d["path"]))
    assert not any(f.startswith("service") for f in os.listdir(dbp))

    # Unknown input file -> in-band error, not an exception.
    bad = CompactionServiceInput(
        cf_name="default", input_files=[999999], output_level=2,
        bottommost=True, snapshots=[], max_output_file_size=1 << 62,
    )
    res2 = CompactionServiceResult.from_json(
        open_and_compact(dbp, outp, bad.to_json())
    )
    assert res2.status != "ok" and "999999" in res2.status
    # Unknown CF -> in-band error.
    res3 = CompactionServiceResult.from_json(
        open_and_compact(dbp, outp, CompactionServiceInput(
            cf_name="nope", input_files=nums, output_level=2,
            bottommost=True, snapshots=[], max_output_file_size=1 << 62,
        ).to_json())
    )
    assert res3.status != "ok"


def test_service_executor_end_to_end(tmp_path):
    """DB routes its compaction through the service executor; results are
    installed under DB-allocated numbers and reads see compacted data."""
    dbp = str(tmp_path / "db")
    svc = InProcessCompactionService()
    db = _fill_db(dbp)
    db.close()

    o = Options(
        disable_auto_compactions=True,
        compaction_executor_factory=CompactionServiceExecutorFactory(svc),
    )
    db = DB.open(dbp, o)
    db.compact_range()
    assert svc.jobs >= 1
    assert db.get(b"key00007") is None
    assert db.get(b"key00008") is not None
    assert db.get(b"key01999") == b"val%06d" % 1999
    # All data now below L0.
    version = db.versions.cf_current(0)
    assert not version.files[0]
    db.close()
    # Reopen cleanly (MANIFEST installed the service outputs).
    db = DB.open(dbp, Options())
    assert db.get(b"key00008") is not None
    db.close()


def test_service_subprocess_transport(tmp_path):
    dbp = str(tmp_path / "db")
    db = _fill_db(dbp, n=800, overwrite=500)
    version = db.versions.cf_current(0)
    nums = [f.number for f in version.files[0]]
    db.close()
    outp = str(tmp_path / "out")
    res = CompactionServiceResult.from_json(SubprocessCompactionService()(
        dbp, outp, CompactionServiceInput(
            cf_name="default", input_files=nums, output_level=2,
            bottommost=True, snapshots=[], max_output_file_size=1 << 62,
        ).to_json()
    ))
    assert res.status == "ok", res.status
    assert res.output_files


def test_service_executor_non_default_cf(tmp_path):
    """Jobs carry the real column family, not 'default' (the worker resolves
    input numbers against that CF's version)."""
    dbp = str(tmp_path / "db")
    svc = InProcessCompactionService()
    o = Options(write_buffer_size=1 << 14, disable_auto_compactions=True)
    db = DB.open(dbp, o)
    cf = db.create_column_family("meta")
    for i in range(2000):
        db.put(b"m%05d" % (i % 900), b"val%06d" % i, cf=cf)
    db.flush()
    db.close()

    o2 = Options(
        disable_auto_compactions=True,
        compaction_executor_factory=CompactionServiceExecutorFactory(
            svc, allow_fallback=False,  # a cf mix-up must FAIL, not fall back
        ),
    )
    db = DB.open(dbp, o2)
    cf = db.get_column_family("meta")
    db.compact_range()  # covers every CF, incl. "meta"
    assert svc.jobs >= 1
    assert db.get(b"m00899", cf=cf) == b"val%06d" % 1799
    version = db.versions.cf_current(cf.id)
    assert not version.files[0]
    db.close()
