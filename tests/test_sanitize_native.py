"""ASan/UBSan replay of the native fuzz corpus (satellite of the
concurrency-correctness plane): rebuild tpulsm_native.cc with
TPULSM_NATIVE_SANITIZE set and drive the same budgeted fuzz targets
through the instrumented .so in a subprocess. A sanitizer report aborts
the child, so a clean exit IS the assertion.

ASan must be loaded before libc allocates, hence the LD_PRELOAD of
libasan in the child environment (the parent process stays
uninstrumented). Skips when the toolchain or the runtime library is
missing.
"""

import os
import shutil
import subprocess
import sys

import pytest

from toplingdb_tpu import native

pytestmark = [pytest.mark.slow,
              pytest.mark.skipif(native.lib() is None,
                                 reason="native library unavailable")]

_CHILD = r"""
import random
from toplingdb_tpu import native
from toplingdb_tpu.tools import fuzz_native as fz

assert native._SANITIZE == {mode!r}, "sanitize mode did not take"
assert native.lib() is not None, "sanitized .so failed to build/load"
rng = random.Random(1234)
total = 0
for target, runs in (("wb", 120), ("block", 120), ("scan", 60),
                     ("manifest", 10)):
    corpus = fz.Corpus({corpus_dir!r} + "/" + target)
    total += fz.TARGETS[target](rng, runs, corpus)
assert total == 0, f"{{total}} finding(s) under sanitizer"
print("SANITIZED_REPLAY_OK")
"""


def _libasan() -> str | None:
    gxx = shutil.which("g++")
    if gxx is None:
        return None
    try:
        out = subprocess.run(
            [gxx, "-print-file-name=libasan.so"], capture_output=True,
            text=True, timeout=30).stdout.strip()
    except (subprocess.SubprocessError, OSError):
        return None
    return out if out and os.path.sep in out and os.path.exists(out) \
        else None


def _replay(mode: str, env_extra: dict, tmp_path) -> None:
    env = dict(os.environ)
    env["TPULSM_NATIVE_SANITIZE"] = mode
    env["JAX_PLATFORMS"] = "cpu"
    env.update(env_extra)
    src = _CHILD.format(mode=mode, corpus_dir=str(tmp_path))
    proc = subprocess.run(
        [sys.executable, "-c", src], capture_output=True, text=True,
        timeout=600, env=env,
        cwd=os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
    if proc.returncode != 0 and "failed to build/load" in \
            (proc.stdout + proc.stderr):
        pytest.skip(f"{mode}-instrumented build unavailable")
    assert proc.returncode == 0, (
        f"sanitized replay died (rc={proc.returncode})\n"
        f"stdout:\n{proc.stdout}\nstderr:\n{proc.stderr}")
    assert "SANITIZED_REPLAY_OK" in proc.stdout


def test_fuzz_corpus_replay_asan(tmp_path):
    lib = _libasan()
    if lib is None:
        pytest.skip("libasan not found")
    _replay("asan", {
        "LD_PRELOAD": lib,
        # ctypes dlopens the .so after interpreter start; leak reports of
        # interpreter-lifetime allocations are noise here.
        "ASAN_OPTIONS": "detect_leaks=0:abort_on_error=1",
    }, tmp_path)


def test_fuzz_corpus_replay_ubsan(tmp_path):
    _replay("undefined", {"UBSAN_OPTIONS": "halt_on_error=1"}, tmp_path)
