"""CompactionIterator state-machine tests, shaped after the reference's
compaction_iterator_test.cc: pure in-memory input, assert exact survivors."""

import pytest

from toplingdb_tpu.compaction.compaction_iterator import CompactionIterator
from toplingdb_tpu.db.dbformat import (
    InternalKeyComparator,
    ValueType,
    make_internal_key,
    split_internal_key,
)
from toplingdb_tpu.db.range_del import RangeDelAggregator, RangeTombstone
from toplingdb_tpu.utils.compaction_filter import CompactionFilter, Decision
from toplingdb_tpu.utils.merge_operator import StringAppendOperator, UInt64AddOperator

ICMP = InternalKeyComparator()


class FakeIter:
    def __init__(self, entries):
        # entries: [(user_key, seq, type, value)] — will be sorted internally.
        items = [
            (make_internal_key(k, s, t), v) for k, s, t, v in entries
        ]
        items.sort(key=lambda kv: _W(kv[0]))
        self._items = items
        self._i = 0

    def valid(self):
        return self._i < len(self._items)

    def key(self):
        return self._items[self._i][0]

    def value(self):
        return self._items[self._i][1]

    def next(self):
        self._i += 1

    def seek_to_first(self):
        self._i = 0


class _W:
    __slots__ = ("k",)

    def __init__(self, k):
        self.k = k

    def __lt__(self, other):
        return ICMP.compare(self.k, other.k) < 0


def run(entries, snapshots=(), bottommost=False, merge_op=None, cfilter=None,
        tombstones=()):
    rd = None
    if tombstones:
        rd = RangeDelAggregator(ICMP.user_comparator)
        for seq, b, e in tombstones:
            rd.add(RangeTombstone(seq, b, e))
    ci = CompactionIterator(
        FakeIter(entries), ICMP, list(snapshots), bottommost_level=bottommost,
        merge_operator=merge_op, compaction_filter=cfilter, range_del_agg=rd,
    )
    out = []
    for ikey, v in ci.entries():
        uk, s, t = split_internal_key(ikey)
        out.append((uk, s, t, v))
    return out, ci


def test_dedup_no_snapshots():
    out, _ = run([
        (b"a", 5, ValueType.VALUE, b"v5"),
        (b"a", 3, ValueType.VALUE, b"v3"),
        (b"b", 4, ValueType.VALUE, b"vb"),
    ])
    assert out == [(b"a", 5, ValueType.VALUE, b"v5"), (b"b", 4, ValueType.VALUE, b"vb")]


def test_snapshot_preserves_old_version():
    out, _ = run([
        (b"a", 5, ValueType.VALUE, b"v5"),
        (b"a", 3, ValueType.VALUE, b"v3"),
    ], snapshots=[4])
    assert out == [
        (b"a", 5, ValueType.VALUE, b"v5"),
        (b"a", 3, ValueType.VALUE, b"v3"),
    ]


def test_multiple_snapshots_stripes():
    out, _ = run([
        (b"a", 9, ValueType.VALUE, b"v9"),
        (b"a", 7, ValueType.VALUE, b"v7"),
        (b"a", 5, ValueType.VALUE, b"v5"),
        (b"a", 3, ValueType.VALUE, b"v3"),
    ], snapshots=[4, 8])
    # Stripes: (8,inf]=v9 | (4,8]=v7 (v5 obsolete) | [0,4]=v3
    assert out == [
        (b"a", 9, ValueType.VALUE, b"v9"),
        (b"a", 7, ValueType.VALUE, b"v7"),
        (b"a", 3, ValueType.VALUE, b"v3"),
    ]


def test_tombstone_kept_above_bottommost():
    out, _ = run([
        (b"a", 5, ValueType.DELETION, b""),
        (b"a", 3, ValueType.VALUE, b"v3"),
    ])
    assert out == [(b"a", 5, ValueType.DELETION, b"")]


def test_tombstone_dropped_at_bottommost():
    out, _ = run([
        (b"a", 5, ValueType.DELETION, b""),
        (b"a", 3, ValueType.VALUE, b"v3"),
        (b"b", 4, ValueType.VALUE, b"vb"),
    ], bottommost=True)
    assert out == [(b"b", 0, ValueType.VALUE, b"vb")]  # seqno zeroed too


def test_tombstone_kept_at_bottommost_with_snapshot():
    out, _ = run([
        (b"a", 5, ValueType.DELETION, b""),
        (b"a", 3, ValueType.VALUE, b"v3"),
    ], snapshots=[4], bottommost=True)
    # The deletion is protected by snapshot 4; the value below the earliest
    # snapshot may legally have its seqno zeroed.
    assert out == [
        (b"a", 5, ValueType.DELETION, b""),
        (b"a", 0, ValueType.VALUE, b"v3"),
    ]


def test_single_delete_annihilates_pair():
    out, ci = run([
        (b"a", 5, ValueType.SINGLE_DELETION, b""),
        (b"a", 3, ValueType.VALUE, b"v3"),
        (b"b", 2, ValueType.VALUE, b"vb"),
    ])
    assert out == [(b"b", 2, ValueType.VALUE, b"vb")]
    assert ci.num_single_del_pairs == 1


def test_single_delete_kept_across_snapshot_boundary():
    out, _ = run([
        (b"a", 5, ValueType.SINGLE_DELETION, b""),
        (b"a", 3, ValueType.VALUE, b"v3"),
    ], snapshots=[4])
    assert out == [
        (b"a", 5, ValueType.SINGLE_DELETION, b""),
        (b"a", 3, ValueType.VALUE, b"v3"),
    ]


def test_unmatched_single_delete_travels():
    out, _ = run([(b"a", 5, ValueType.SINGLE_DELETION, b"")])
    assert out == [(b"a", 5, ValueType.SINGLE_DELETION, b"")]
    out, _ = run([(b"a", 5, ValueType.SINGLE_DELETION, b"")], bottommost=True)
    assert out == []


def test_merge_fold_onto_base():
    op = StringAppendOperator()
    out, ci = run([
        (b"a", 5, ValueType.MERGE, b"m2"),
        (b"a", 4, ValueType.MERGE, b"m1"),
        (b"a", 3, ValueType.VALUE, b"base"),
    ], merge_op=op)
    assert out == [(b"a", 5, ValueType.VALUE, b"base,m1,m2")]


def test_merge_fold_over_delete():
    op = StringAppendOperator()
    out, _ = run([
        (b"a", 5, ValueType.MERGE, b"m1"),
        (b"a", 4, ValueType.DELETION, b""),
        (b"a", 3, ValueType.VALUE, b"old"),
    ], merge_op=op)
    # Delete cuts the chain; merge result becomes a Put superseding it.
    assert out == [(b"a", 5, ValueType.VALUE, b"m1")]


def test_merge_partial_merge_without_base():
    op = UInt64AddOperator()
    import struct

    out, _ = run([
        (b"a", 5, ValueType.MERGE, struct.pack("<Q", 3)),
        (b"a", 4, ValueType.MERGE, struct.pack("<Q", 4)),
    ], merge_op=op)
    # No base in inputs and not bottommost: operands combine into one MERGE.
    assert out == [(b"a", 5, ValueType.MERGE, struct.pack("<Q", 7))]


def test_merge_finalized_at_bottommost():
    op = UInt64AddOperator()
    import struct

    out, _ = run([
        (b"a", 5, ValueType.MERGE, struct.pack("<Q", 3)),
        (b"a", 4, ValueType.MERGE, struct.pack("<Q", 4)),
    ], merge_op=op, bottommost=True)
    # Folded to a VALUE; at the bottommost level its seqno is zeroed.
    assert out == [(b"a", 0, ValueType.VALUE, struct.pack("<Q", 7))]


def test_merge_respects_snapshot_stripes():
    op = StringAppendOperator()
    out, _ = run([
        (b"a", 6, ValueType.MERGE, b"new"),
        (b"a", 3, ValueType.MERGE, b"old"),
    ], snapshots=[4], merge_op=op)
    # Operands in different stripes must not combine.
    assert out == [
        (b"a", 6, ValueType.MERGE, b"new"),
        (b"a", 3, ValueType.MERGE, b"old"),
    ]


def test_range_tombstone_drops_covered():
    out, ci = run([
        (b"b", 3, ValueType.VALUE, b"vb"),
        (b"x", 4, ValueType.VALUE, b"vx"),
    ], tombstones=[(10, b"a", b"c")])
    assert out == [(b"x", 4, ValueType.VALUE, b"vx")]
    assert ci.num_dropped_tombstone == 1


def test_range_tombstone_respects_stripe():
    out, _ = run([
        (b"b", 3, ValueType.VALUE, b"vb"),
    ], snapshots=[5], tombstones=[(10, b"a", b"c")])
    # Snapshot at 5 must still see b@3; tombstone@10 is in a newer stripe.
    assert out == [(b"b", 3, ValueType.VALUE, b"vb")]


def test_compaction_filter_removes():
    class DropOdd(CompactionFilter):
        def name(self):
            return "DropOdd"

        def filter(self, level, key, value):
            if int(key[-1:] or b"0") % 2:
                return Decision.REMOVE, None
            return Decision.KEEP, None

    out, ci = run([
        (b"k1", 3, ValueType.VALUE, b"v"),
        (b"k2", 4, ValueType.VALUE, b"v"),
    ], cfilter=DropOdd())
    assert [o[0] for o in out] == [b"k2"]
    assert ci.num_dropped_filtered == 1


def test_compaction_filter_change_value():
    class Rewrite(CompactionFilter):
        def name(self):
            return "Rewrite"

        def filter(self, level, key, value):
            return Decision.CHANGE_VALUE, b"rewritten"

    out, _ = run([(b"k", 3, ValueType.VALUE, b"v")], cfilter=Rewrite())
    assert out[0][3] == b"rewritten"


def test_compaction_filter_skips_snapshot_protected():
    class DropAll(CompactionFilter):
        def name(self):
            return "DropAll"

        def filter(self, level, key, value):
            return Decision.REMOVE, None

    out, _ = run([(b"k", 6, ValueType.VALUE, b"v")], snapshots=[3], cfilter=DropAll())
    # Entry newer than a snapshot is not handed to the filter.
    assert out == [(b"k", 6, ValueType.VALUE, b"v")]


def test_seqno_zeroing_only_at_bottommost():
    out, _ = run([(b"k", 6, ValueType.VALUE, b"v")])
    assert out == [(b"k", 6, ValueType.VALUE, b"v")]
    out, _ = run([(b"k", 6, ValueType.VALUE, b"v")], bottommost=True)
    assert out == [(b"k", 0, ValueType.VALUE, b"v")]
    out, _ = run([(b"k", 6, ValueType.VALUE, b"v")], snapshots=[3], bottommost=True)
    assert out == [(b"k", 6, ValueType.VALUE, b"v")]  # protected by snapshot
