"""Whitebox crash testing — TEST_KILL_RANDOM kill points + the db_stress
--whitebox crash loop (reference tools/db_crashtest.py whitebox mode)."""

import os
import subprocess
import sys
import textwrap

import pytest

from toplingdb_tpu.utils.kill_point import KILLED_EXIT_CODE, reset_for_tests
from toplingdb_tpu.utils.kill_point import test_kill_random as kill_marker


def test_unarmed_is_noop(monkeypatch):
    monkeypatch.delenv("TPULSM_KILL_ODDS", raising=False)
    reset_for_tests()
    for _ in range(100):
        kill_marker("VersionSet::LogAndApply:BeforeManifestWrite")
    reset_for_tests()


def test_prefix_filter_spares_other_points(monkeypatch):
    monkeypatch.setenv("TPULSM_KILL_ODDS", "1")  # certain death if armed
    monkeypatch.setenv("TPULSM_KILL_PREFIX", "FlushJob")
    reset_for_tests()
    kill_marker("DBImpl::WriteImpl:AfterWAL")  # not armed: survives
    reset_for_tests()


def test_armed_point_kills_subprocess():
    src = textwrap.dedent("""
        import sys
        sys.path.insert(0, %r)
        from toplingdb_tpu.utils.kill_point import test_kill_random
        test_kill_random("FlushJob::AfterTableWrite")
        print("survived")
    """ % os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
    env = dict(os.environ, TPULSM_KILL_ODDS="1", TPULSM_KILL_SEED="7")
    r = subprocess.run([sys.executable, "-c", src], env=env,
                       capture_output=True)
    assert r.returncode == KILLED_EXIT_CODE
    assert b"survived" not in r.stdout


@pytest.mark.parametrize("prefix", [
    "DBImpl::WriteImpl:AfterWAL",
    "FlushJob::AfterTableWrite",
    "VersionSet::LogAndApply",
])
def test_whitebox_crash_loop_recovers(tmp_path, prefix):
    """Arm one durability window at a time; the crash loop must recover and
    verify after every fired kill point."""
    db = str(tmp_path / "db")
    cmd = [
        sys.executable, "-m", "toplingdb_tpu.tools.db_stress",
        f"--db={db}", "--crash-test", "--whitebox",
        "--rounds=3", "--ops=4000", "--threads=2", "--max-key=300",
        "--kill-odds=40", f"--kill-prefix={prefix}",
        "--kill-after=30", "--seed=11",
        "--write-buffer-size=8192",  # frequent switches/flushes
    ]
    env = dict(os.environ)
    env["PYTHONPATH"] = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    r = subprocess.run(cmd, capture_output=True, timeout=240, env=env)
    out = r.stdout.decode()
    assert r.returncode == 0, out + r.stderr.decode()
    assert "crash test passed" in out


def test_crash_matrix_driver_smoke():
    """The db_crashtest matrix driver (reference tools/db_crashtest.py
    parameter sweep role): two cells under a tiny budget must pass and
    print the summary line."""
    import subprocess
    import sys

    r = subprocess.run(
        [sys.executable, "-m", "toplingdb_tpu.tools.db_crashtest",
         "--duration", "16", "--variants", "blob", "--modes",
         "blackbox,whitebox", "--ops", "8000"],
        capture_output=True, text=True, timeout=300,
    )
    assert r.returncode == 0, r.stdout + r.stderr
    assert "MATRIX PASSED" in r.stdout
