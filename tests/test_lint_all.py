"""The unified tier-1 lint driver (tools/lint_all.py).

One invocation runs every static correctness plane — telemetry,
concurrency, native-abi, errors — with per-checker exit semantics
preserved in the report and a single aggregate exit code. The whole run
must stay inside a 10s tier-1 budget.
"""

import textwrap

from toplingdb_tpu.tools import lint_all

_BUDGET_S = 10.0


def test_real_tree_clean_within_budget():
    violations, results = lint_all.run()
    assert violations == []
    # Every plane ran, none was silently skipped.
    assert set(results) == {"native-abi", "telemetry", "errors",
                            "concurrency"}
    for name, (found, _dt) in results.items():
        assert found == [], (name, found)
    assert sum(dt for _, dt in results.values()) < _BUDGET_S


def test_cli_exit_zero_and_per_checker_report(capsys):
    assert lint_all.main([]) == 0
    out = capsys.readouterr().out
    for name in ("native-abi", "telemetry", "errors", "concurrency"):
        assert f"lint_all: {name:<12} exit=0" in out
    assert "0 violation(s) total" in out


def test_single_nonzero_exit_on_any_finding(tmp_path, capsys):
    """A violation in ONE plane must flip the aggregate exit code while
    the per-checker report still attributes it to that plane."""
    pkg = tmp_path / "toplingdb_tpu"
    pkg.mkdir()
    (pkg / "bad.py").write_text(textwrap.dedent("""\
        def f():
            try:
                g()
            except Exception:
                pass
        """))
    assert lint_all.main([str(tmp_path)]) == 1
    out = capsys.readouterr().out
    assert "bad.py:4:" in out  # the finding's witness survives aggregation
    assert "lint_all: errors" in out and "exit=1" in out


def test_crashed_checker_is_a_finding(tmp_path):
    """An analyzer that cannot even parse its inputs must fail the run,
    not vanish from it (a missing native source tree crashes the ABI
    parse)."""
    (tmp_path / "toplingdb_tpu").mkdir()
    violations, results = lint_all.run(str(tmp_path))
    assert any("native-abi" in v and "crashed" in v for v in violations) \
        or results["native-abi"][0], violations
    assert violations
