"""CuckooTable format: 2-probe point lookups, displacement build, adaptive
dispatch, DB read-path integration (reference table/cuckoo/)."""

import random

import pytest

from toplingdb_tpu.db.dbformat import (
    BYTEWISE, InternalKeyComparator, ValueType, make_internal_key,
)
from toplingdb_tpu.env import MemEnv
from toplingdb_tpu.table.builder import TableOptions
from toplingdb_tpu.table.cuckoo import (
    CuckooTableBuilder,
    CuckooTableReader,
    _bucket_pair,
)
from toplingdb_tpu.table.factory import new_table_builder, open_table
from toplingdb_tpu.utils.status import NotSupported

ICMP = InternalKeyComparator(BYTEWISE)


def build_cuckoo(env, path, keys, opts=None):
    opts = opts or TableOptions(format="cuckoo")
    w = env.new_writable_file(path)
    b = new_table_builder(w, ICMP, opts)
    assert isinstance(b, CuckooTableBuilder)
    entries = []
    for i, uk in enumerate(sorted(keys)):
        ik = make_internal_key(uk, i + 1, ValueType.VALUE)
        v = b"v-" + uk
        b.add(ik, v)
        entries.append((ik, v))
    props = b.finish()
    w.close()
    return entries, props


def test_cuckoo_roundtrip_probe_and_dispatch():
    env = MemEnv()
    keys = [b"key%05d" % i for i in range(500)]
    entries, props = build_cuckoo(env, "/c.sst", keys)
    r = open_table(env.new_random_access_file("/c.sst"), ICMP)
    assert isinstance(r, CuckooTableReader)  # adaptive magic dispatch
    assert r.has_hash_index
    assert r.properties.num_entries == 500
    # Every present key resolves through at most two buckets.
    for ik, v in entries:
        i = r.hash_probe(ik[:-8])
        assert i is not None and r._entry(i) == (ik, v)
    # Absent keys are definitively rejected.
    for uk in (b"nope", b"key99999", b""):
        assert r.hash_probe(uk) is None
    # Ordered iteration comes from the sorted data region.
    it = r.new_iterator()
    it.seek_to_first()
    assert list(it.entries()) == entries


def test_cuckoo_displacement_stress():
    """Random keys at high load force displacement chains (and possibly
    growth); every key must still resolve."""
    env = MemEnv()
    rng = random.Random(42)
    keys = list({b"k%016x" % rng.getrandbits(60) for _ in range(4000)})
    entries, _ = build_cuckoo(env, "/big.sst", keys)
    r = open_table(env.new_random_access_file("/big.sst"), ICMP)
    for ik, v in entries:
        i = r.hash_probe(ik[:-8])
        assert i is not None and r._entry(i)[1] == v
    # The index holds every key in one of its two candidate buckets.
    mask = len(r._buckets) - 1
    for ik, _ in entries:
        b1, b2 = _bucket_pair(ik[:-8], mask)
        ordinals = {int(r._buckets[b1]) - 1, int(r._buckets[b2]) - 1}
        assert r._lower_bound(ik) in ordinals


def test_cuckoo_rejects_duplicates_and_range_dels():
    env = MemEnv()
    w = env.new_writable_file("/dup.sst")
    b = new_table_builder(w, ICMP, TableOptions(format="cuckoo"))
    b.add(make_internal_key(b"aaa", 5, ValueType.VALUE), b"v1")
    with pytest.raises(NotSupported):
        b.add(make_internal_key(b"aaa", 3, ValueType.VALUE), b"v0")
    with pytest.raises(NotSupported):
        b.add_tombstone(
            make_internal_key(b"b", 9, ValueType.RANGE_DELETION), b"c"
        )


def test_cuckoo_compaction_output_and_db_get(tmp_path):
    """A bottommost compaction can emit cuckoo files (unique user keys after
    GC), and the DB read path probes them through the adaptive factory."""
    from toplingdb_tpu.compaction.compaction_job import run_compaction_to_tables
    from toplingdb_tpu.compaction.picker import Compaction
    from toplingdb_tpu.db.table_cache import TableCache
    from toplingdb_tpu.db.version_edit import FileMetaData
    from toplingdb_tpu.env import default_env
    from toplingdb_tpu.table.builder import TableBuilder
    import toplingdb_tpu.db.filename as fn

    env = default_env()
    dbdir = str(tmp_path)
    block_opts = TableOptions(block_size=512)
    metas = []
    seq = 1
    rng = random.Random(3)
    for fnum in (61, 62):
        entries = []
        for _ in range(200):
            k = b"key%04d" % rng.randrange(250)
            entries.append(
                (make_internal_key(k, seq, ValueType.VALUE), b"val%05d" % seq)
            )
            seq += 1
        entries.sort(key=lambda kv: ICMP.sort_key(kv[0]))
        w = env.new_writable_file(fn.table_file_name(dbdir, fnum))
        b = TableBuilder(w, ICMP, block_opts)
        last = None
        for k, v in entries:
            if last == k:
                continue
            b.add(k, v)
            last = k
        props = b.finish()
        w.close()
        metas.append(FileMetaData(
            number=fnum,
            file_size=env.get_file_size(fn.table_file_name(dbdir, fnum)),
            smallest=b.smallest_key, largest=b.largest_key,
            smallest_seqno=props.smallest_seqno,
            largest_seqno=props.largest_seqno,
        ))
    tc = TableCache(env, dbdir, ICMP, block_opts)
    c = Compaction(level=0, output_level=2, inputs=metas, bottommost=True,
                   max_output_file_size=1 << 62)
    cnt = [100]

    def alloc():
        cnt[0] += 1
        return cnt[0]

    outs, _ = run_compaction_to_tables(
        env, dbdir, ICMP, c, tc, TableOptions(format="cuckoo"), [],
        new_file_number=alloc, creation_time=1,
    )
    assert outs
    r = open_table(
        env.new_random_access_file(fn.table_file_name(dbdir, outs[0].number)),
        ICMP,
    )
    assert isinstance(r, CuckooTableReader)
    it = r.new_iterator()
    it.seek_to_first()
    got = list(it.entries())
    # bottommost GC: one version per user key, seqs zeroed
    uks = [k[:-8] for k, _ in got]
    assert len(set(uks)) == len(uks) == r.properties.num_entries > 0
    for ik, v in got:
        assert r.hash_probe(ik[:-8]) is not None


def test_cuckoo_empty_table_and_fail_fast():
    env = MemEnv()
    # Empty table: writable AND readable (valid empty index).
    w = env.new_writable_file("/e.sst")
    b = new_table_builder(w, ICMP, TableOptions(format="cuckoo"))
    b.finish()
    w.close()
    r = open_table(env.new_random_access_file("/e.sst"), ICMP)
    assert isinstance(r, CuckooTableReader)
    assert r.hash_probe(b"anything") is None
    it = r.new_iterator()
    it.seek_to_first()
    assert not it.valid()
    # Non-bytewise comparator: refused at construction, before any bytes.
    from toplingdb_tpu.db.dbformat import Comparator

    class Rev(Comparator):
        def name(self):
            return "test.reverse"

        def compare(self, a, b):
            return (a < b) - (a > b)

    w2 = env.new_writable_file("/r.sst")
    with pytest.raises(NotSupported):
        new_table_builder(w2, InternalKeyComparator(Rev()),
                          TableOptions(format="cuckoo"))


def test_cuckoo_failed_job_leaves_no_orphans(tmp_path):
    """A mid-stream NotSupported (duplicate user keys survive under a
    snapshot) must fail the compaction WITHOUT leaving partial outputs."""
    from toplingdb_tpu.compaction.compaction_job import run_compaction_to_tables
    from toplingdb_tpu.compaction.picker import Compaction
    from toplingdb_tpu.db.table_cache import TableCache
    from toplingdb_tpu.db.version_edit import FileMetaData
    from toplingdb_tpu.env import default_env
    from toplingdb_tpu.table.builder import TableBuilder
    import os
    import toplingdb_tpu.db.filename as fn

    env = default_env()
    dbdir = str(tmp_path)
    block_opts = TableOptions(block_size=512)
    metas = []
    seq = 1
    for fnum in (71, 72):
        entries = []
        for i in range(100):
            entries.append((
                make_internal_key(b"key%04d" % i, seq, ValueType.VALUE),
                b"val%05d" % seq,
            ))
            seq += 1
        entries.sort(key=lambda kv: ICMP.sort_key(kv[0]))
        w = env.new_writable_file(fn.table_file_name(dbdir, fnum))
        b = TableBuilder(w, ICMP, block_opts)
        for k, v in entries:
            b.add(k, v)
        props = b.finish()
        w.close()
        metas.append(FileMetaData(
            number=fnum,
            file_size=env.get_file_size(fn.table_file_name(dbdir, fnum)),
            smallest=b.smallest_key, largest=b.largest_key,
            smallest_seqno=props.smallest_seqno,
            largest_seqno=props.largest_seqno,
        ))
    tc = TableCache(env, dbdir, ICMP, block_opts)
    c = Compaction(level=0, output_level=2, inputs=metas, bottommost=True,
                   max_output_file_size=4096)  # several outputs
    cnt = [300]

    def alloc():
        cnt[0] += 1
        return cnt[0]

    before = set(os.listdir(dbdir))
    with pytest.raises(NotSupported):
        # snapshot 150 keeps two versions of early keys → duplicate user
        # keys reach the cuckoo builder mid-stream.
        run_compaction_to_tables(
            env, dbdir, ICMP, c, tc, TableOptions(format="cuckoo"), [150],
            new_file_number=alloc, creation_time=1,
        )
    assert set(os.listdir(dbdir)) == before, "orphan outputs left behind"
