"""Mesh compaction execution mode (ops/mesh_compaction.py +
parallel/mesh_plan.py): byte parity with the single-chip plane across
codecs x block/zip x range tombstones x snapshots, mid-job chip-failure
demotion, the eligibility/fallback matrix, and the dcompact worker's
pod-level chip pool (per-chip queues, wedge demotion, /metrics gauges).

Runs on the conftest-provided 8 virtual CPU devices
(--xla_force_host_platform_device_count); mesh runs are capped to 2 chips
via TPULSM_MESH_DEVICES so per-device jit compiles stay affordable."""

import json
import urllib.request

import pytest

from test_compaction_pipeline import (
    ICMP,
    _build_runs,
    _mk_alloc,
    _run_job,
    _sst_bytes,
)
from toplingdb_tpu.parallel import mesh_plan


def _mesh_env(monkeypatch, on: bool, devices: int = 2):
    from toplingdb_tpu.ops import device_compaction as dc

    monkeypatch.setattr(dc, "_SHARD_MIN_ROWS", 1)
    monkeypatch.setenv("TPULSM_DEVICE_SHARDS", "4")
    monkeypatch.setenv("TPULSM_MESH_MIN_ROWS", "1")
    monkeypatch.setenv("TPULSM_MESH_DEVICES", str(devices))
    if on:
        monkeypatch.setenv("TPULSM_MESH_COMPACT", "1")
    else:
        monkeypatch.delenv("TPULSM_MESH_COMPACT", raising=False)


@pytest.mark.parametrize("fmt_name,codec", [
    ("block", "none"), ("block", "zstd"),
    ("zip", "none"), ("zip", "zstd"),
])
def test_mesh_byte_parity(tmp_path, monkeypatch, fmt_name, codec):
    """Mesh outputs are byte-identical to the single-chip sharded plane
    for block and zip emission, with a surviving range tombstone and live
    snapshots in the job — the ISSUE's parity matrix."""
    from toplingdb_tpu.compaction.scheduler import CompactionScheduler
    from toplingdb_tpu.env import default_env
    from toplingdb_tpu.table import format as fmt
    from toplingdb_tpu.utils import codecs

    if codec != "none" and not codecs.available(codec):
        pytest.skip(f"{codec} unavailable")
    from toplingdb_tpu.table.builder import TableOptions

    comp = {"none": fmt.NO_COMPRESSION,
            "zstd": fmt.ZSTD_COMPRESSION}[codec]
    env = default_env()
    dbdir = str(tmp_path)
    topts = TableOptions(block_size=512)
    out_topts = TableOptions(block_size=512, compression=comp) \
        if fmt_name == "block" else \
        TableOptions(format="zip", compression=comp)
    n = 9_000
    metas = _build_runs(env, dbdir, n, topts, seed=3, tombstone_file=True)
    snapshots = [n // 3, 2 * n // 3]

    _mesh_env(monkeypatch, on=False)
    out_ref, ref_stats = _run_job(env, dbdir, metas, topts, out_topts,
                                  1000, snapshots)
    assert getattr(ref_stats, "mesh_chips", 0) == 0

    _mesh_env(monkeypatch, on=True)
    out_mesh, stats = _run_job(env, dbdir, metas, topts, out_topts,
                               2000, snapshots)
    assert stats.mesh_chips == 2, "mesh plane did not engage"
    assert stats.mesh_shards >= 2
    assert CompactionScheduler._compaction_mode(stats) == "mesh"

    assert len(out_ref) == len(out_mesh) >= 1
    assert _sst_bytes(env, dbdir, out_mesh) == \
        _sst_bytes(env, dbdir, out_ref), \
        f"{fmt_name}/{codec}: mesh SST bytes differ from single-chip"


@pytest.mark.parametrize("kill_all", [False, True])
def test_mesh_chip_failure_demotion(tmp_path, monkeypatch, kill_all):
    """A chip that dies mid-job wedges: its shards re-dispatch on the
    survivors (kill_all=False) or the default device (kill_all=True) and
    the job completes with byte-identical outputs — zero corrupted or
    partial files. Demotions are counted on stats.mesh_fallbacks."""
    from toplingdb_tpu.env import default_env
    from toplingdb_tpu.ops import mesh_compaction as mc
    from toplingdb_tpu.table.builder import TableOptions

    env = default_env()
    dbdir = str(tmp_path)
    topts = TableOptions(block_size=512)
    n = 9_000
    metas = _build_runs(env, dbdir, n, topts, seed=4, tombstone_file=True)
    snapshots = [n // 2]

    _mesh_env(monkeypatch, on=False)
    out_ref, _ = _run_job(env, dbdir, metas, topts, topts, 1000, snapshots)

    _mesh_env(monkeypatch, on=True)
    dead = set()
    limit = 2 if kill_all else 1

    def hook(_s, device):
        if device is None:
            return  # default device must stay healthy
        if str(device) in dead:
            raise RuntimeError("chip down")
        if len(dead) < limit:
            dead.add(str(device))
            raise RuntimeError("chip down")

    monkeypatch.setattr(mc, "_FAULT_HOOK", hook)
    out_mesh, stats = _run_job(env, dbdir, metas, topts, topts, 2000,
                               snapshots)
    assert len(dead) == limit
    assert stats.mesh_fallbacks >= limit
    assert stats.mesh_chips == 1  # demoted from the 2-chip plan
    assert _sst_bytes(env, dbdir, out_mesh) == \
        _sst_bytes(env, dbdir, out_ref), "demoted job bytes differ"


def test_mesh_pipeline_parity(tmp_path, monkeypatch):
    """The pipelined plane's compute stage places shards over the mesh
    too (ops/pipeline.py _device_compute): bytes match the mesh-off
    pipelined run and the mode engages on stats."""
    from test_compaction_pipeline import _enable_small_pipeline
    from toplingdb_tpu.env import default_env
    from toplingdb_tpu.table.builder import TableOptions

    monkeypatch.setenv("TPULSM_PIPELINE", "1")
    _enable_small_pipeline(monkeypatch)
    env = default_env()
    dbdir = str(tmp_path)
    topts = TableOptions(block_size=512)
    n = 9_000
    metas = _build_runs(env, dbdir, n, topts, seed=5, tombstone_file=True)
    snapshots = [n // 3]

    _mesh_env(monkeypatch, on=False)
    out_ref, _ = _run_job(env, dbdir, metas, topts, topts, 1000, snapshots)
    _mesh_env(monkeypatch, on=True)
    out_mesh, stats = _run_job(env, dbdir, metas, topts, topts, 2000,
                               snapshots)
    assert stats.mesh_chips == 2, "pipeline mesh placement did not engage"
    assert _sst_bytes(env, dbdir, out_mesh) == \
        _sst_bytes(env, dbdir, out_ref), "pipelined mesh bytes differ"


def test_eligibility_matrix():
    """mesh_plan.check_eligibility is the one fallback matrix: every
    reason string, and the happy-path plan shape."""
    devs = ["d0", "d1", "d2"]
    shards = mesh_plan._make_uniform_shards(4, 64, key_len=20)

    assert mesh_plan.check_eligibility(None, False, devs)[0] == \
        "no-uniform-shards"
    assert mesh_plan.check_eligibility([], False, devs)[0] == \
        "no-uniform-shards"
    assert mesh_plan.check_eligibility(shards[:1], False, devs,
                                       min_rows=1)[0] == "single-shard"
    assert mesh_plan.check_eligibility(shards, True, devs,
                                       min_rows=1)[0] == "complex-groups"
    assert mesh_plan.check_eligibility(shards, False, devs,
                                       min_rows=10**9)[0] == \
        "below-row-floor"
    assert mesh_plan.check_eligibility(shards, False, devs[:1],
                                       min_rows=1)[0] == "single-device"
    reason, total = mesh_plan.check_eligibility(shards, False, devs,
                                                min_rows=1)
    assert reason is None and total == 4 * 64

    plan, reason = mesh_plan.plan_shards(shards, devices=devs, min_rows=1)
    assert reason is None
    assert plan.assignments == [0, 1, 2, 0]
    assert plan.n_devices == 3
    assert plan.window == mesh_plan.UPLOAD_DEPTH * 3

    plan, reason = mesh_plan.plan_shards(shards, any_complex=True,
                                         devices=devs, min_rows=1)
    assert plan is None and reason == "complex-groups"


def test_maybe_plan_gating(monkeypatch):
    """Knob off -> None with no fallback tick; knob on + ineligible ->
    None WITH a fallback tick; knob on + eligible -> plan + stats."""
    from toplingdb_tpu.compaction.compaction_job import CompactionStats
    from toplingdb_tpu.ops import mesh_compaction as mc

    shards = mesh_plan._make_uniform_shards(4, 64, key_len=20)
    monkeypatch.delenv("TPULSM_MESH_COMPACT", raising=False)
    stats = CompactionStats()
    assert mc.maybe_plan(shards, stats=stats) is None
    assert stats.mesh_fallbacks == 0

    monkeypatch.setenv("TPULSM_MESH_COMPACT", "1")
    monkeypatch.setenv("TPULSM_MESH_MIN_ROWS", "1")
    monkeypatch.setenv("TPULSM_MESH_DEVICES", "2")
    assert mc.maybe_plan(shards, any_complex=True, stats=stats) is None
    assert stats.mesh_fallbacks == 1

    plan = mc.maybe_plan(shards, stats=stats)
    assert plan is not None and plan.n_devices == 2
    assert stats.mesh_chips == 2 and stats.mesh_shards == 4


def test_mesh_statistics_tickers():
    """CompactionStats mesh fields land on the DCOMPACTION_MESH_* tickers
    through Statistics.record_compaction."""
    from toplingdb_tpu.compaction.compaction_job import CompactionStats
    from toplingdb_tpu.utils import statistics as st

    stats = st.Statistics()
    cs = CompactionStats(device="cpu")
    cs.mesh_chips = 4
    cs.mesh_shards = 16
    cs.mesh_fallbacks = 2
    stats.record_compaction(cs)
    t = stats.tickers()
    assert t[st.DCOMPACTION_MESH_JOBS] == 1
    assert t[st.DCOMPACTION_MESH_SHARDS] == 16
    assert t[st.DCOMPACTION_MESH_FALLBACKS] == 2

    # Single-chip jobs don't tick the mesh counters.
    stats2 = st.Statistics()
    stats2.record_compaction(CompactionStats(device="cpu"))
    t2 = stats2.tickers()
    assert st.DCOMPACTION_MESH_JOBS not in t2


def test_chip_pool_admission_and_demotion():
    """ChipPool: least-loaded targeting, wedge-aware demotion, failure
    feedback through the chip breakers, and queue-depth accounting."""
    from toplingdb_tpu.compaction.dcompact_service import ChipPool

    pool = ChipPool(4)
    g1 = pool.admit(want=2)
    assert len(g1) == 2
    # Next grant targets the two idle chips (least depth first).
    g2 = pool.admit(want=2)
    assert len(g2) == 2 and not set(g1) & set(g2)
    depths = pool.queue_depths()
    assert all(depths[c] == 1 for c in g1 + g2)
    pool.release(g1, ok=True)
    pool.release(g2, ok=True)
    assert all(v == 0 for v in pool.queue_depths().values())

    # Open chip:0's breaker: it drops out of future grants.
    for _ in range(3):
        pool.health.record_failure("chip:0")
    g3 = pool.admit()
    assert "chip:0" not in g3 and len(g3) == 3
    pool.release(g3, ok=True)

    # A full-pool failure opens every breaker -> admit returns [] (the
    # caller runs local) instead of blocking forever.
    pool2 = ChipPool(2)
    for _ in range(3):
        g = pool2.admit()
        pool2.release(g, ok=False, failed_chips=set(g))
    assert pool2.admit(timeout=0.1) == []


def test_chip_pool_timeout_partial_grant():
    """A gang-wait that times out takes the free subset instead of
    stalling the job behind a busy chip."""
    from toplingdb_tpu.compaction.dcompact_service import ChipPool

    pool = ChipPool(2)
    hold = pool.admit(want=1)
    assert len(hold) == 1
    g = pool.admit(want=2, timeout=0.15)
    assert len(g) == 1 and g[0] not in hold
    pool.release(g)
    pool.release(hold)
    assert all(v == 0 for v in pool.queue_depths().values())


def test_service_chip_metrics(tmp_path):
    """DcompactWorkerService --chips exposes per-chip queue-depth /
    busy / wedged gauges on /metrics and the pool snapshot on /stats."""
    from toplingdb_tpu.compaction.dcompact_service import (
        DcompactWorkerService,
    )

    svc = DcompactWorkerService(device="cpu", chips=2)
    port = svc.start()
    try:
        for _ in range(3):
            svc.pool.health.record_failure("chip:1")
        with urllib.request.urlopen(
                f"http://127.0.0.1:{port}/metrics") as r:
            body = r.read().decode()
        assert 'tpulsm_dcompact_chip_queue_depth{chip="chip:0"} 0' in body
        assert 'tpulsm_dcompact_chip_wedged{chip="chip:1"} 1' in body
        assert 'tpulsm_dcompact_chip_busy{chip="chip:0"} 0' in body
        with urllib.request.urlopen(
                f"http://127.0.0.1:{port}/stats") as r:
            stats = json.loads(r.read())
        assert stats["chips"]["chip:1"]["state"] == "open"
        assert stats["chips"]["chip:0"]["queue_depth"] == 0
    finally:
        svc.stop()


def test_probe_cli_exit_codes(monkeypatch, capsys):
    """scaling_probe distinguishes skip (environment) from failure
    (measurement): requesting more devices than exist is EXIT_SKIP."""
    import os

    from toplingdb_tpu.parallel import scaling_probe

    # configure_virtual_devices rewrites these; pin them so monkeypatch
    # restores the suite's 8-device flags afterwards.
    for k in ("XLA_FLAGS", "JAX_PLATFORMS", "PALLAS_AXON_POOL_IPS"):
        monkeypatch.setenv(k, os.environ.get(k, ""))
    rc = scaling_probe.main(["--devices", "4096"])
    out = capsys.readouterr().out
    assert rc == mesh_plan.EXIT_SKIP
    assert "skip" in json.loads(out.strip().splitlines()[-1])

    def boom(*a, **k):
        raise RuntimeError("measurement broke")

    monkeypatch.setattr(mesh_plan, "weak_scaling_rows", boom)
    rc = scaling_probe.main(["--devices", "1", "--rows-per-device", "64"])
    out = capsys.readouterr().out
    assert rc == mesh_plan.EXIT_FAILURE
    assert "error" in json.loads(out.strip().splitlines()[-1])
