"""SingleFastTable format + adaptive factory dispatch."""

import pytest

from toplingdb_tpu.db.db import DB
from toplingdb_tpu.db.dbformat import (
    BYTEWISE, InternalKeyComparator, ValueType, make_internal_key,
)
from toplingdb_tpu.env import MemEnv
from toplingdb_tpu.options import Options
from toplingdb_tpu.table.builder import TableOptions
from toplingdb_tpu.table.factory import new_table_builder, open_table
from toplingdb_tpu.table.single_fast import SingleFastTableReader
from toplingdb_tpu.utils.status import Corruption

ICMP = InternalKeyComparator(BYTEWISE)


def build_sf(env, path, n=300, tombstones=()):
    opts = TableOptions(format="single_fast")
    w = env.new_writable_file(path)
    b = new_table_builder(w, ICMP, opts)
    entries = [
        (make_internal_key(b"key%05d" % i, i + 1, ValueType.VALUE),
         b"val%06d" % i)
        for i in range(n)
    ]
    for k, v in entries:
        b.add(k, v)
    for begin, end in tombstones:
        b.add_tombstone(begin, end)
    props = b.finish()
    w.close()
    return entries, props


def test_single_fast_roundtrip_and_dispatch():
    env = MemEnv()
    entries, props = build_sf(env, "/t.sst")
    r = open_table(env.new_random_access_file("/t.sst"), ICMP,
                   TableOptions(format="single_fast"))
    assert isinstance(r, SingleFastTableReader)  # adaptive magic dispatch
    assert r.properties.num_entries == 300
    it = r.new_iterator()
    it.seek_to_first()
    assert list(it.entries()) == entries


def test_single_fast_seek_prev_bloom():
    env = MemEnv()
    entries, _ = build_sf(env, "/t.sst")
    r = open_table(env.new_random_access_file("/t.sst"), ICMP)
    it = r.new_iterator()
    it.seek(make_internal_key(b"key00150", 2**56 - 1, 0x7F))
    assert it.key() == entries[150][0]
    it.prev()
    assert it.key() == entries[149][0]
    it.seek_to_last()
    assert it.key() == entries[-1][0]
    assert r.key_may_match(b"key00001")
    misses = sum(1 for i in range(1000) if r.key_may_match(b"no%05d" % i))
    assert misses < 60


def test_single_fast_checksum_detects_corruption():
    env = MemEnv()
    build_sf(env, "/t.sst")
    st = env._files["/t.sst"]
    st.data[40] ^= 0xFF
    with pytest.raises(Corruption):
        open_table(env.new_random_access_file("/t.sst"), ICMP)


def test_single_fast_range_del_and_anchors():
    env = MemEnv()
    begin = make_internal_key(b"key00010", 999, ValueType.RANGE_DELETION)
    entries, props = build_sf(env, "/t.sst", tombstones=[(begin, b"key00020")])
    r = open_table(env.new_random_access_file("/t.sst"), ICMP)
    assert r.range_del_entries() == [(begin, b"key00020")]
    anchors = r.anchors(8)
    assert 1 <= len(anchors) <= 8


def test_db_with_single_fast_format(tmp_db_path):
    """Full DB stack on the single_fast format: flush, compaction (the
    device fast path must fall back), reopen, CFs, deletes."""
    opts = Options(
        write_buffer_size=8 * 1024,
        table_options=TableOptions(format="single_fast"),
    )
    with DB.open(tmp_db_path, opts) as db:
        for i in range(3000):
            db.put(b"key%05d" % (i % 1000), b"val%07d" % i)
        db.delete(b"key00007")
        db.flush()
        db.compact_range()
        assert db.get(b"key00007") is None
        for k in range(0, 1000, 83):
            if k == 7:
                continue
            last = max(i for i in range(k, 3000, 1000))
            assert db.get(b"key%05d" % k) == b"val%07d" % last
        it = db.new_iterator()
        it.seek_to_first()
        assert sum(1 for _ in it.entries()) == 999
    with DB.open(tmp_db_path, opts) as db:
        assert db.get(b"key00500") == b"val%07d" % 2500


def test_mixed_formats_in_one_db(tmp_db_path):
    """Adaptive dispatch: files written as single_fast stay readable after
    the DB switches to the block format (and vice versa)."""
    sf = Options(write_buffer_size=8 * 1024,
                 table_options=TableOptions(format="single_fast"),
                 disable_auto_compactions=True)
    with DB.open(tmp_db_path, sf) as db:
        for i in range(500):
            db.put(b"sf%04d" % i, b"1")
        db.flush()
    blk = Options(write_buffer_size=8 * 1024, disable_auto_compactions=True)
    with DB.open(tmp_db_path, blk) as db:
        for i in range(500):
            db.put(b"bb%04d" % i, b"2")
        db.flush()
        assert db.get(b"sf0250") == b"1"   # single_fast file via adaptive open
        assert db.get(b"bb0250") == b"2"   # block file
        db.compact_range()                  # merges both formats
        assert db.get(b"sf0250") == b"1"
        assert db.get(b"bb0250") == b"2"


def test_hash_index_point_lookups(tmp_db_path):
    """single_fast + hash_index: O(1) bucket probes serve point lookups
    (the CuckooTable role); versions/snapshots/misses behave identically."""
    from toplingdb_tpu.db.db import DB
    from toplingdb_tpu.options import Options, ReadOptions

    o = Options(disable_auto_compactions=True)
    o.table_options.format = "single_fast"
    o.table_options.hash_index = True
    with DB.open(tmp_db_path, o) as db:
        for i in range(2000):
            db.put(b"key%05d" % i, b"v1-%05d" % i)
        snap = db.get_snapshot()
        for i in range(0, 2000, 2):
            db.put(b"key%05d" % i, b"v2-%05d" % i)
        db.flush()
        f = db.versions.current.files[0][0]
        r = db.table_cache.get_reader(f.number)
        assert r.has_hash_index
        assert r.hash_probe(b"key00042") is not None
        assert r.hash_probe(b"nope") is None
        assert db.get(b"key00042") == b"v2-00042"
        assert db.get(b"key00043") == b"v1-00043"
        assert db.get(b"missing") is None
        assert db.get(b"key00042", ReadOptions(snapshot=snap)) == b"v1-00042"
        snap.release()
    with DB.open(tmp_db_path, o) as db:
        assert db.get(b"key01999") == b"v1-01999"
        assert db.get(b"key01998") == b"v2-01998"


def test_hash_index_vs_binary_same_results(tmp_db_path):
    from toplingdb_tpu.db.db import DB
    from toplingdb_tpu.options import Options

    o = Options(disable_auto_compactions=True)
    o.table_options.format = "single_fast"
    o.table_options.hash_index = True
    with DB.open(tmp_db_path, o) as db:
        for i in range(500):
            db.put(b"k%04d" % (i * 7 % 997), b"v%04d" % i)
        db.flush()
        f = db.versions.current.files[0][0]
        r = db.table_cache.get_reader(f.number)
        it = r.new_iterator()
        it.seek_to_first()
        for ikey, _ in it.entries():
            uk = ikey[:-8]
            j = r.hash_probe(uk)
            assert j is not None
            assert r._entry(j)[0][:-8] == uk
