"""Regenerate tests/golden/dbv1 (run from the repo root).

ONLY run this when an INTENTIONAL format change lands — the golden dir
exists to catch unintentional ones. Regeneration must be deterministic:
frozen clock, fixed data. Commit the regenerated dir together with the
format change and note it in the commit message.
"""

import shutil
import uuid
from unittest import mock

from toplingdb_tpu.db.db import DB
from toplingdb_tpu.options import Options
from toplingdb_tpu.table import format as fmt

_FIXED_UUID = uuid.UUID("0" * 31 + "1")


def main(dest: str = "tests/golden/dbv1") -> None:
    shutil.rmtree(dest, ignore_errors=True)
    with mock.patch("time.time", lambda: 1753750000.0), \
            mock.patch("uuid.uuid4", lambda: _FIXED_UUID):
        o = Options(write_buffer_size=1 << 20, disable_auto_compactions=True,
                    enable_blob_files=True, min_blob_size=64)
        o.table_options.compression = fmt.ZLIB_COMPRESSION
        with DB.open(dest, o) as db:
            cf = db.create_column_family("meta")
            for i in range(500):
                db.put(b"key%04d" % i, b"value-%04d" % i)
            db.put(b"big", b"B" * 500)          # blob-separated
            db.put(b"mk", b"mv", cf=cf)
            db.delete(b"key0100")
            db.delete_range(b"key0200", b"key0210")
            db.flush()
            db.put(b"wal-tail", b"unflushed")   # stays in the WAL
            db._wal.sync()
            db._closed = True                   # crash-style: WAL replay


if __name__ == "__main__":
    main()
