"""Ticker/histogram breadth: the reference's stat families populate from
real engine activity (VERDICT r2 task 6)."""

import threading


def test_read_write_iter_stats_populate(tmp_path):
    from toplingdb_tpu.db.db import DB
    from toplingdb_tpu.options import Options, WriteOptions
    from toplingdb_tpu.utils import statistics as st

    stats = st.Statistics()
    opts = Options(create_if_missing=True, write_buffer_size=64 * 1024,
                   statistics=stats)
    with DB.open(str(tmp_path / "db"), opts) as db:
        for i in range(2000):
            db.put(b"key%05d" % i, b"v" * 20)
        db.put(b"sync", b"x", WriteOptions(sync=True))
        db.flush()
        for i in range(0, 2000, 7):
            assert db.get(b"key%05d" % i) is not None
        assert db.get(b"missing-key") is None
        it = db.new_iterator()
        it.seek(b"key01000")
        for _ in range(50):
            it.next()
        it.prev()
        db.compact_range()
        db.wait_for_compactions()

        g = stats.get_ticker_count
        assert g(st.NUMBER_KEYS_WRITTEN) >= 2001
        assert g(st.BYTES_WRITTEN) > 0
        assert g(st.WRITE_DONE_BY_SELF) > 0
        assert g(st.WRITE_WITH_WAL) >= 2001
        assert g(st.WAL_BYTES) > 0
        assert g(st.WAL_SYNCS) >= 1
        assert g(st.NUMBER_KEYS_READ) >= 287
        assert g(st.MEMTABLE_HIT) + g(st.MEMTABLE_MISS) >= 287
        assert (g(st.GET_HIT_L0) + g(st.GET_HIT_L1)
                + g(st.GET_HIT_L2_AND_UP)) > 0
        assert g(st.NUMBER_DB_SEEK) >= 1
        assert g(st.NUMBER_DB_NEXT) >= 50
        assert g(st.NUMBER_DB_PREV) >= 1
        assert g(st.ITER_BYTES_READ) > 0
        assert g(st.NO_ITERATOR_CREATED) >= 1
        assert g(st.NO_FILE_OPENS) >= 1
        assert g(st.FLUSH_WRITE_BYTES) > 0
        assert g(st.COMPACT_WRITE_BYTES) > 0
        assert g(st.COMPACTION_KEY_DROP_OBSOLETE) >= 0
        assert stats.get_histogram(st.DB_GET_MICROS).count >= 287
        assert stats.get_histogram(st.WAL_FILE_SYNC_MICROS).count >= 1
        assert stats.get_histogram(st.TABLE_OPEN_IO_MICROS).count >= 1
        assert stats.get_histogram(
            st.NUM_FILES_IN_SINGLE_COMPACTION).count >= 1
        # stats dump shows the families
        dump = stats.to_string()
        assert "lcompaction" in dump or "dcompaction" in dump


def test_dcompact_timing_breakdown(tmp_path):
    """A real worker run populates prepare/waiting/work (and the D* split)
    — the reference CompactionResults timing fields,
    compaction_executor.h:146-150."""
    from toplingdb_tpu.compaction.executor import (
        SubprocessCompactionExecutorFactory,
    )
    from toplingdb_tpu.db.db import DB
    from toplingdb_tpu.options import Options
    from toplingdb_tpu.utils import statistics as st

    stats = st.Statistics()
    opts = Options(
        create_if_missing=True, write_buffer_size=8 * 1024,
        statistics=stats,
        compaction_executor_factory=SubprocessCompactionExecutorFactory(
            device="cpu"),
    )
    with DB.open(str(tmp_path / "db"), opts) as db:
        for i in range(3000):
            db.put(b"key%05d" % (i % 1000), b"val%07d" % i)
        db.flush()
        db.compact_range()
        db.wait_for_compactions()
    assert stats.get_ticker_count(st.DCOMPACTION_READ_BYTES) > 0
    assert stats.get_histogram(st.DCOMPACTION_TIME_MICROS).count >= 1
    assert stats.get_histogram(st.DCOMPACTION_PREPARE_MICROS).count >= 1
    assert stats.get_histogram(st.DCOMPACTION_WAITING_MICROS).count >= 1
    assert stats.get_histogram(st.DCOMPACTION_RPC_MICROS).count >= 1


def test_txn_tickers(tmp_path):
    from toplingdb_tpu.options import Options
    from toplingdb_tpu.utilities.transactions import TransactionDB
    from toplingdb_tpu.utils import statistics as st

    stats = st.Statistics()
    tdb = TransactionDB.open(str(tmp_path / "tdb"),
                             Options(create_if_missing=True,
                                     statistics=stats))
    t = tdb.begin_transaction()
    t.put(b"a", b"1")
    t.commit()
    t2 = tdb.begin_transaction()
    t2.put(b"b", b"2")
    t2.rollback()
    tdb.close()
    assert stats.get_ticker_count(st.TXN_COMMIT) == 1
    assert stats.get_ticker_count(st.TXN_ROLLBACK) == 1


def test_perf_context_breadth():
    from toplingdb_tpu.utils.statistics import PerfContext, perf_context

    assert len(PerfContext._FIELDS) >= 50
    ctx = perf_context()
    ctx.reset()
    d = ctx.to_dict()
    assert len(d) >= 50 and all(v == 0 for v in d.values())


def test_perf_context_populates(tmp_path):
    from toplingdb_tpu.db.db import DB
    from toplingdb_tpu.options import Options
    from toplingdb_tpu.utils import statistics as st

    with DB.open(str(tmp_path / "db"), Options(create_if_missing=True)) as db:
        for i in range(500):
            db.put(b"k%05d" % i, b"v" * 10)
        db.flush()
        ctx = st.perf_context()
        ctx.reset()
        # Collection is opt-in (reference SetPerfLevel; disabled default).
        st.perf_level = 1
        try:
            for i in range(0, 500, 9):
                db.get(b"k%05d" % i)
            assert ctx.get_from_memtable_count > 0
            assert ctx.block_read_count > 0
            assert ctx.block_read_byte > 0
            assert ctx.bloom_sst_hit_count > 0
            db.get(b"k0025zz")  # inside file key range, absent
            assert ctx.bloom_sst_miss_count >= 1
        finally:
            st.perf_level = 0


def test_multiget_stats(tmp_path):
    from toplingdb_tpu.db.db import DB
    from toplingdb_tpu.options import Options
    from toplingdb_tpu.utils import statistics as st

    stats = st.Statistics()
    with DB.open(str(tmp_path / "db"),
                 Options(create_if_missing=True, statistics=stats)) as db:
        for i in range(100):
            db.put(b"k%03d" % i, b"val-%03d" % i)
        res = db.multi_get([b"k001", b"k050", b"nope"])
        assert res[0] == b"val-001" and res[2] is None
    assert stats.get_ticker_count(st.NUMBER_MULTIGET_CALLS) == 1
    assert stats.get_ticker_count(st.NUMBER_MULTIGET_KEYS_READ) == 3
    assert stats.get_ticker_count(st.NUMBER_MULTIGET_BYTES_READ) == 14
    assert stats.get_histogram(st.DB_MULTIGET_MICROS).count == 1


def test_prometheus_metrics_endpoint(tmp_path):
    """GET /metrics serves Prometheus text over every registered DB's
    statistics (the rockside WebView/Prometheus role)."""
    import urllib.request

    from toplingdb_tpu.db.db import DB
    from toplingdb_tpu.options import Options
    from toplingdb_tpu.utils import statistics as st
    from toplingdb_tpu.utils.config import SidePluginRepo

    stats = st.Statistics()
    db = DB.open(str(tmp_path / "db"),
                 Options(create_if_missing=True, statistics=stats))
    try:
        for i in range(50):
            db.put(b"k%02d" % i, b"v")
        repo = SidePluginRepo()
        repo._dbs["main"] = db  # register an externally-opened DB
        port = repo.start_http()
        body = urllib.request.urlopen(
            f"http://127.0.0.1:{port}/metrics", timeout=10).read().decode()
        repo.stop_http()
        assert "# TYPE tpulsm_number_keys_written counter" in body
        assert 'tpulsm_number_keys_written{db="main"} 50' in body
        assert "tpulsm_db_write_micros_count" in body
        assert 'quantile="0.99"' in body
    finally:
        db.close()
