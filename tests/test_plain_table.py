"""PlainTable format + SliceTransform / prefix-bloom / prefix-iteration.

Covers the reference's table/plain/ (prefix hash index, binary search in
bucket), SliceTransform (include/rocksdb/slice_transform.h), prefix bloom
filters (whole_key_filtering=false) and ReadOptions.prefix_same_as_start.
"""

import pytest

from toplingdb_tpu.db import dbformat
from toplingdb_tpu.db.dbformat import BYTEWISE, InternalKeyComparator
from toplingdb_tpu.env.env import default_env
from toplingdb_tpu.options import Options, ReadOptions
from toplingdb_tpu.table.builder import TableOptions
from toplingdb_tpu.table.factory import new_table_builder, open_table
from toplingdb_tpu.table.plain import PlainTableReader
from toplingdb_tpu.utils.slice_transform import (
    CappedPrefixTransform,
    FixedPrefixTransform,
    NoopTransform,
    slice_transform_from_name,
)
from toplingdb_tpu.utils.status import InvalidArgument

ICMP = InternalKeyComparator(BYTEWISE)


def ik(uk: bytes, seq: int = 1, t: int = dbformat.ValueType.VALUE) -> bytes:
    return dbformat.make_internal_key(uk, seq, t)


# -- SliceTransform ------------------------------------------------------

def test_slice_transforms():
    f = FixedPrefixTransform(3)
    assert f.transform(b"abcdef") == b"abc"
    assert f.in_domain(b"abc") and not f.in_domain(b"ab")
    c = CappedPrefixTransform(3)
    assert c.transform(b"ab") == b"ab" and c.in_domain(b"a")
    n = NoopTransform()
    assert n.transform(b"xy") == b"xy"
    for t in (f, c, n):
        rt = slice_transform_from_name(t.name())
        assert rt is not None and rt.name() == t.name()
    assert slice_transform_from_name("custom.whatever") is None


# -- plain table build/read ---------------------------------------------

def _build_plain(tmp_path, entries, topts=None):
    env = default_env()
    topts = topts or TableOptions(
        format="plain", prefix_extractor=FixedPrefixTransform(4)
    )
    path = str(tmp_path / "t.sst")
    b = new_table_builder(env.new_writable_file(path), ICMP, topts)
    for k, v in entries:
        b.add(k, v)
    b.finish()
    return env, path, topts


def test_plain_requires_extractor(tmp_path):
    env = default_env()
    with pytest.raises(InvalidArgument):
        new_table_builder(
            env.new_writable_file(str(tmp_path / "x.sst")), ICMP,
            TableOptions(format="plain"),
        )


def test_plain_build_and_probe(tmp_path):
    entries = []
    for grp in (b"aaaa", b"bbbb", b"cccc"):
        for i in range(5):
            entries.append((ik(grp + b"%02d" % i, seq=10 + i), b"v" + grp))
    # short (out-of-domain) key
    entries.append((ik(b"zz", seq=3), b"short"))
    entries.sort(key=lambda kv: ICMP.sort_key(kv[0]))
    env, path, topts = _build_plain(tmp_path, entries)

    r = open_table(env.new_random_access_file(path), ICMP, topts)
    assert isinstance(r, PlainTableReader)
    assert r.has_hash_index
    assert r.properties.prefix_extractor_name.startswith("tpulsm.FixedPrefix")

    # in-domain hits: newest version ordinal
    o = r.hash_probe(b"bbbb03")
    assert o is not None
    assert r._entry(o)[0][:-8] == b"bbbb03"
    # in-domain miss within an existing group
    assert r.hash_probe(b"bbbb99") is None
    # miss: nonexistent prefix group
    assert r.hash_probe(b"qqqq00") is None
    # out-of-domain fallback
    o = r.hash_probe(b"zz")
    assert o is not None and r._entry(o)[1] == b"short"
    assert r.hash_probe(b"z") is None
    # prefix group entry point
    s = r.prefix_seek_start(b"cccc")
    assert s is not None and r._entry(s)[0][:-8] == b"cccc00"
    assert r.prefix_seek_start(b"dddd") is None
    # iteration still total-order
    it = r.new_iterator()
    it.seek_to_first()
    keys = [k for k, _ in it.entries()]
    assert keys == [k for k, _ in entries]


def test_plain_newest_version_wins(tmp_path):
    entries = [
        (ik(b"aaaak", seq=9), b"new"),
        (ik(b"aaaak", seq=5), b"old"),
    ]
    env, path, topts = _build_plain(tmp_path, entries)
    r = open_table(env.new_random_access_file(path), ICMP, topts)
    o = r.hash_probe(b"aaaak")
    assert r._entry(o)[1] == b"new"


# -- prefix bloom --------------------------------------------------------

def test_prefix_only_filter_block_format(tmp_path):
    env = default_env()
    topts = TableOptions(
        prefix_extractor=FixedPrefixTransform(4), whole_key_filtering=False
    )
    path = str(tmp_path / "b.sst")
    b = new_table_builder(env.new_writable_file(path), ICMP, topts)
    n = 0
    for g in range(3):
        for i in range(70):
            n += 1
            b.add(ik(b"pre%d-%04d" % (g, i), seq=n), b"v")
    b.finish()
    r = open_table(env.new_random_access_file(path), ICMP)
    assert r.properties.whole_key_filtering == 0
    # same prefix, absent key → filter can NOT rule it out
    assert r.key_may_match(b"pre0-9999")
    # absent prefix → almost surely ruled out
    hits = sum(r.key_may_match(b"zzz%d-far" % i) for i in range(50))
    assert hits <= 5
    # prefix probe surface
    assert r.prefix_may_match(b"pre0")


# -- end-to-end DB with plain format + prefix iteration ------------------

def test_db_plain_format_end_to_end(tmp_path):
    from toplingdb_tpu.db.db import DB

    opts = Options(
        prefix_extractor=FixedPrefixTransform(4),
        table_options=TableOptions(format="plain"),
        write_buffer_size=1 << 20,
        memtable_rep="hash_skiplist:4",
    )
    db = DB.open(str(tmp_path / "db"), opts)
    for g in (b"user", b"item", b"sess"):
        for i in range(30):
            db.put(g + b"%03d" % i, b"val-" + g + b"%03d" % i)
    db.flush()
    db.put(b"user001", b"overwritten")  # in memtable, over an SST value
    assert db.get(b"user005") == b"val-user005"
    assert db.get(b"user001") == b"overwritten"
    assert db.get(b"none999") is None

    # prefix_same_as_start: stops at the end of the prefix group
    it = db.new_iterator(ReadOptions(prefix_same_as_start=True))
    it.seek(b"item010")
    got = [k for k, _ in it.entries()]
    assert got == [b"item%03d" % i for i in range(10, 30)]

    # total_order_seek overrides prefix mode
    it = db.new_iterator(
        ReadOptions(prefix_same_as_start=True, total_order_seek=True)
    )
    it.seek(b"item010")
    got = [k for k, _ in it.entries()]
    assert got[-1] == b"user029" and len(got) == 20 + 30 + 30

    db.close()


def test_db_plain_compaction_roundtrip(tmp_path):
    from toplingdb_tpu.db.db import DB

    opts = Options(
        prefix_extractor=FixedPrefixTransform(4),
        table_options=TableOptions(format="plain"),
        level0_file_num_compaction_trigger=100,  # manual compact only
    )
    db = DB.open(str(tmp_path / "db"), opts)
    for i in range(50):
        db.put(b"pfx%05d" % i, b"v%d" % i)
    db.flush()
    for i in range(0, 50, 2):
        db.put(b"pfx%05d" % i, b"w%d" % i)
    db.flush()
    db.compact_range()
    for i in range(50):
        want = b"w%d" % i if i % 2 == 0 else b"v%d" % i
        assert db.get(b"pfx%05d" % i) == want
    db.close()


def test_extractor_change_across_reopen(tmp_path):
    """Old files keep answering probes via their RECORDED extractor even
    when the live options extractor changed (resolve_file_extractor)."""
    entries = [(ik(b"aaaabbbb", seq=4), b"v1"), (ik(b"ccccdddd", seq=5), b"v2")]
    env, path, _ = _build_plain(
        tmp_path, entries,
        TableOptions(format="plain", prefix_extractor=FixedPrefixTransform(4)),
    )
    # reopen with an 8-byte extractor: probes must still hit
    r = open_table(
        env.new_random_access_file(path), ICMP,
        TableOptions(format="plain", prefix_extractor=FixedPrefixTransform(8)),
    )
    o = r.hash_probe(b"aaaabbbb")
    assert o is not None and r._entry(o)[1] == b"v1"
    # prefix-only bloom, same scenario: no false negatives
    topts = TableOptions(
        prefix_extractor=FixedPrefixTransform(4), whole_key_filtering=False
    )
    p2 = str(tmp_path / "b2.sst")
    b = new_table_builder(env.new_writable_file(p2), ICMP, topts)
    b.add(ik(b"aaaabbbb", seq=1), b"v")
    b.finish()
    r2 = open_table(
        env.new_random_access_file(p2), ICMP,
        TableOptions(prefix_extractor=FixedPrefixTransform(8)),
    )
    assert r2.key_may_match(b"aaaabbbb")


def test_seek_to_first_with_lower_bound_is_total_order(tmp_path):
    from toplingdb_tpu.db.db import DB

    opts = Options(prefix_extractor=FixedPrefixTransform(2))
    db = DB.open(str(tmp_path / "db"), opts)
    db.put(b"aab", b"1")
    db.put(b"ac1", b"2")
    it = db.new_iterator(ReadOptions(
        prefix_same_as_start=True, iterate_lower_bound=b"aa"
    ))
    it.seek_to_first()
    assert [k for k, _ in it.entries()] == [b"aab", b"ac1"]
    # but an explicit Seek still arms prefix mode
    it = db.new_iterator(ReadOptions(prefix_same_as_start=True))
    it.seek(b"aa")
    assert [k for k, _ in it.entries()] == [b"aab"]
    db.close()


def test_options_config_roundtrip_prefix():
    from toplingdb_tpu.utils.config import (
        options_from_config, options_to_config,
    )

    opts = Options(prefix_extractor=FixedPrefixTransform(7))
    cfg = options_to_config(opts)
    assert cfg["prefix_extractor"]["params"]["length"] == 7
    opts2 = options_from_config(cfg)
    assert opts2.prefix_extractor.name() == opts.prefix_extractor.name()
