from toplingdb_tpu.db.dbformat import (
    InternalKeyComparator,
    ValueType,
    make_internal_key,
    split_internal_key,
)
from toplingdb_tpu.db.memtable import MemTable

ICMP = InternalKeyComparator()
MAXSEQ = 2**56 - 1


def test_versions_newest_first():
    m = MemTable(ICMP)
    m.add(1, ValueType.VALUE, b"k", b"v1")
    m.add(5, ValueType.VALUE, b"k", b"v5")
    m.add(3, ValueType.VALUE, b"k", b"v3")
    assert [s for s, _, _ in m.entries_for_key(b"k", MAXSEQ)] == [5, 3, 1]
    # Snapshot at 4 hides seq 5.
    assert [s for s, _, _ in m.entries_for_key(b"k", 4)] == [3, 1]


def test_iteration_order():
    m = MemTable(ICMP)
    m.add(2, ValueType.VALUE, b"b", b"vb")
    m.add(1, ValueType.VALUE, b"a", b"va")
    m.add(3, ValueType.DELETION, b"a", b"")
    keys = [split_internal_key(k)[:2] for k, _ in m.iter_entries()]
    assert keys == [(b"a", 3), (b"a", 1), (b"b", 2)]


def test_range_tombstone_coverage():
    m = MemTable(ICMP)
    m.add(10, ValueType.RANGE_DELETION, b"c", b"g")
    assert m.covering_tombstone_seq(b"c", MAXSEQ) == 10
    assert m.covering_tombstone_seq(b"f", MAXSEQ) == 10
    assert m.covering_tombstone_seq(b"g", MAXSEQ) == 0  # end exclusive
    assert m.covering_tombstone_seq(b"b", MAXSEQ) == 0
    assert m.covering_tombstone_seq(b"d", 9) == 0  # snapshot before tombstone


def test_memtable_iterator_protocol():
    m = MemTable(ICMP)
    for i in range(10):
        m.add(i + 1, ValueType.VALUE, b"k%02d" % i, b"v%d" % i)
    it = m.new_iterator()
    it.seek_to_first()
    assert it.valid()
    ks = []
    while it.valid():
        ks.append(split_internal_key(it.key())[0])
        it.next()
    assert ks == [b"k%02d" % i for i in range(10)]
    it.seek(make_internal_key(b"k05", MAXSEQ, 0x7F))
    assert split_internal_key(it.key())[0] == b"k05"
    it.prev()
    assert split_internal_key(it.key())[0] == b"k04"
    it.seek_to_last()
    assert split_internal_key(it.key())[0] == b"k09"


def test_iterator_stable_under_concurrent_insert():
    m = MemTable(ICMP)
    for i in range(0, 20, 2):
        m.add(i + 1, ValueType.VALUE, b"k%02d" % i, b"v")
    it = m.new_iterator()
    it.seek_to_first()
    seen = [split_internal_key(it.key())[0]]
    # Insert new keys while iterating; iterator must not skip/repeat.
    m.add(100, ValueType.VALUE, b"k01", b"new")
    it.next()
    seen.append(split_internal_key(it.key())[0])
    assert seen == [b"k00", b"k01"]


def test_hash_prefix_rep_matches_skiplist_semantics(tmp_path):
    """hash_skiplist rep (prefix-bucketed): same DB behavior as the default
    rep — ordered scans, reverse iteration, version visibility."""
    import random

    from toplingdb_tpu.db.db import DB
    from toplingdb_tpu.options import Options

    rng = random.Random(5)
    dumps = {}
    for rep in ("skiplist", "hash_skiplist"):
        d = str(tmp_path / rep)
        db = DB.open(d, Options(write_buffer_size=1 << 22, memtable_rep=rep,
                                disable_auto_compactions=True))
        model = {}
        for i in range(3000):
            k = b"key%05d" % rng.randrange(2000)
            if rng.random() < 0.85:
                v = b"v%05d" % i
                db.put(k, v); model[k] = v
            else:
                db.delete(k); model.pop(k, None)
        rng = random.Random(5)  # same sequence for both reps
        for k in (b"key00000", b"key01000", b"key01999", b"zzz"):
            assert db.get(k) == model.get(k)
        it = db.new_iterator()
        it.seek_to_first()
        fwd = list(it.entries())
        assert fwd == sorted(model.items())
        it2 = db.new_iterator()
        it2.seek_to_last()
        rev = []
        while it2.valid():
            rev.append((it2.key(), it2.value()))
            it2.prev()
        assert rev == fwd[::-1]
        it3 = db.new_iterator()
        it3.seek(b"key01000")
        assert it3.valid()
        dumps[rep] = fwd
        db.close()
    assert dumps["skiplist"] == dumps["hash_skiplist"]


def test_hash_prefix_rep_unit():
    from toplingdb_tpu.db.memtable import HashPrefixRep

    r = HashPrefixRep(prefix_len=3)
    import random

    rng = random.Random(1)
    keys = []
    for i in range(500):
        uk = b"%03d-%04d" % (rng.randrange(20), i)
        skey = (uk, rng.randrange(1 << 32))
        keys.append(skey)
        r.insert(skey, b"v%d" % i)
    assert len(r) == 500
    ordered = [k for k, _ in r.iter_all()]
    assert ordered == sorted(keys)
    # Cursor walk equals iter_all.
    walked = []
    pos = r.pos_first()
    while pos is not None:
        walked.append(r.entry_at(pos)[0])
        pos = r.pos_next(pos)
    assert walked == ordered
    # seek_ge / seek_lt on bucket boundaries.
    mid = sorted(keys)[250]
    assert r.entry_at(r.pos_seek_ge(mid))[0] == mid
    lt = r.pos_seek_lt(mid)
    assert r.entry_at(lt)[0] == sorted(keys)[249]
    assert r.pos_seek_lt(sorted(keys)[0]) is None
    assert r.pos_seek_ge((b"\xff\xff\xff\xff", 0)) is None


def test_columnar_flush_byte_parity(tmp_path):
    """The single-native-call columnar flush (MemTable.export_columnar +
    write_tables_columnar) must produce byte-identical SSTs to the
    per-entry iterator path (reference FlushJob::WriteLevel0Table,
    /root/reference/db/flush_job.cc:833) — including deletions, duplicate
    user keys across seqnos, and range tombstones."""
    import random

    from toplingdb_tpu.db import filename as fn
    from toplingdb_tpu.db.dbformat import InternalKeyComparator, ValueType
    from toplingdb_tpu.db.flush_job import flush_memtable_to_table
    from toplingdb_tpu.db.memtable import (
        MemTable,
        NativeSkipListRep,
        PyVectorRep,
    )
    from toplingdb_tpu.env import default_env
    from toplingdb_tpu.table.builder import TableOptions

    try:
        native_rep = NativeSkipListRep()
    except RuntimeError:
        import pytest

        pytest.skip("native library unavailable")
    icmp = InternalKeyComparator()
    env = default_env()

    def fill(mem, n=20000):
        rng = random.Random(7)
        seq = 1
        for i in range(n):
            k = b"k%07d" % rng.randrange(n // 3)
            t = (ValueType.DELETION if rng.random() < 0.1
                 else ValueType.VALUE)
            v = b"" if t == ValueType.DELETION else b"val%d" % i
            mem.add(seq, t, k, v)
            seq += 1
        mem.add(seq, ValueType.RANGE_DELETION, b"k0000100", b"k0000300")

    m1 = MemTable(icmp, native_rep)
    fill(m1)
    m2 = MemTable(icmp, PyVectorRep())
    fill(m2)
    d = str(tmp_path)
    topts = TableOptions(block_size=4096)
    # The parity assertion is only meaningful if the fast path actually
    # engages for m1 — a silent fallback would compare slow vs slow.
    from toplingdb_tpu.db import flush_job as fj

    calls = []
    orig = fj._flush_columnar

    def spy(*a, **kw):
        r = orig(*a, **kw)
        calls.append(r)
        return r

    fj._flush_columnar = spy
    try:
        meta1 = flush_memtable_to_table(env, d, 11, icmp, [m1], topts,
                                        creation_time=5)
    finally:
        fj._flush_columnar = orig
    assert calls and calls[0] is not None, "columnar fast path did not run"
    meta2 = flush_memtable_to_table(env, d, 12, icmp, [m2], topts,
                                    creation_time=5)
    b1 = open(fn.table_file_name(d, 11), "rb").read()
    b2 = open(fn.table_file_name(d, 12), "rb").read()
    assert b1 == b2
    assert meta1.num_entries == meta2.num_entries == 20000
    assert meta1.num_range_deletions == 1
    assert meta1.smallest == meta2.smallest
    assert meta1.largest == meta2.largest
