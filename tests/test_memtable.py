from toplingdb_tpu.db.dbformat import (
    InternalKeyComparator,
    ValueType,
    make_internal_key,
    split_internal_key,
)
from toplingdb_tpu.db.memtable import MemTable

ICMP = InternalKeyComparator()
MAXSEQ = 2**56 - 1


def test_versions_newest_first():
    m = MemTable(ICMP)
    m.add(1, ValueType.VALUE, b"k", b"v1")
    m.add(5, ValueType.VALUE, b"k", b"v5")
    m.add(3, ValueType.VALUE, b"k", b"v3")
    assert [s for s, _, _ in m.entries_for_key(b"k", MAXSEQ)] == [5, 3, 1]
    # Snapshot at 4 hides seq 5.
    assert [s for s, _, _ in m.entries_for_key(b"k", 4)] == [3, 1]


def test_iteration_order():
    m = MemTable(ICMP)
    m.add(2, ValueType.VALUE, b"b", b"vb")
    m.add(1, ValueType.VALUE, b"a", b"va")
    m.add(3, ValueType.DELETION, b"a", b"")
    keys = [split_internal_key(k)[:2] for k, _ in m.iter_entries()]
    assert keys == [(b"a", 3), (b"a", 1), (b"b", 2)]


def test_range_tombstone_coverage():
    m = MemTable(ICMP)
    m.add(10, ValueType.RANGE_DELETION, b"c", b"g")
    assert m.covering_tombstone_seq(b"c", MAXSEQ) == 10
    assert m.covering_tombstone_seq(b"f", MAXSEQ) == 10
    assert m.covering_tombstone_seq(b"g", MAXSEQ) == 0  # end exclusive
    assert m.covering_tombstone_seq(b"b", MAXSEQ) == 0
    assert m.covering_tombstone_seq(b"d", 9) == 0  # snapshot before tombstone


def test_memtable_iterator_protocol():
    m = MemTable(ICMP)
    for i in range(10):
        m.add(i + 1, ValueType.VALUE, b"k%02d" % i, b"v%d" % i)
    it = m.new_iterator()
    it.seek_to_first()
    assert it.valid()
    ks = []
    while it.valid():
        ks.append(split_internal_key(it.key())[0])
        it.next()
    assert ks == [b"k%02d" % i for i in range(10)]
    it.seek(make_internal_key(b"k05", MAXSEQ, 0x7F))
    assert split_internal_key(it.key())[0] == b"k05"
    it.prev()
    assert split_internal_key(it.key())[0] == b"k04"
    it.seek_to_last()
    assert split_internal_key(it.key())[0] == b"k09"


def test_iterator_stable_under_concurrent_insert():
    m = MemTable(ICMP)
    for i in range(0, 20, 2):
        m.add(i + 1, ValueType.VALUE, b"k%02d" % i, b"v")
    it = m.new_iterator()
    it.seek_to_first()
    seen = [split_internal_key(it.key())[0]]
    # Insert new keys while iterating; iterator must not skip/repeat.
    m.add(100, ValueType.VALUE, b"k01", b"new")
    it.next()
    seen.append(split_internal_key(it.key())[0])
    assert seen == [b"k00", b"k01"]
