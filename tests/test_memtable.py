from toplingdb_tpu.db.dbformat import (
    InternalKeyComparator,
    ValueType,
    make_internal_key,
    split_internal_key,
)
from toplingdb_tpu.db.memtable import MemTable

ICMP = InternalKeyComparator()
MAXSEQ = 2**56 - 1


def test_versions_newest_first():
    m = MemTable(ICMP)
    m.add(1, ValueType.VALUE, b"k", b"v1")
    m.add(5, ValueType.VALUE, b"k", b"v5")
    m.add(3, ValueType.VALUE, b"k", b"v3")
    assert [s for s, _, _ in m.entries_for_key(b"k", MAXSEQ)] == [5, 3, 1]
    # Snapshot at 4 hides seq 5.
    assert [s for s, _, _ in m.entries_for_key(b"k", 4)] == [3, 1]


def test_iteration_order():
    m = MemTable(ICMP)
    m.add(2, ValueType.VALUE, b"b", b"vb")
    m.add(1, ValueType.VALUE, b"a", b"va")
    m.add(3, ValueType.DELETION, b"a", b"")
    keys = [split_internal_key(k)[:2] for k, _ in m.iter_entries()]
    assert keys == [(b"a", 3), (b"a", 1), (b"b", 2)]


def test_range_tombstone_coverage():
    m = MemTable(ICMP)
    m.add(10, ValueType.RANGE_DELETION, b"c", b"g")
    assert m.covering_tombstone_seq(b"c", MAXSEQ) == 10
    assert m.covering_tombstone_seq(b"f", MAXSEQ) == 10
    assert m.covering_tombstone_seq(b"g", MAXSEQ) == 0  # end exclusive
    assert m.covering_tombstone_seq(b"b", MAXSEQ) == 0
    assert m.covering_tombstone_seq(b"d", 9) == 0  # snapshot before tombstone


def test_memtable_iterator_protocol():
    m = MemTable(ICMP)
    for i in range(10):
        m.add(i + 1, ValueType.VALUE, b"k%02d" % i, b"v%d" % i)
    it = m.new_iterator()
    it.seek_to_first()
    assert it.valid()
    ks = []
    while it.valid():
        ks.append(split_internal_key(it.key())[0])
        it.next()
    assert ks == [b"k%02d" % i for i in range(10)]
    it.seek(make_internal_key(b"k05", MAXSEQ, 0x7F))
    assert split_internal_key(it.key())[0] == b"k05"
    it.prev()
    assert split_internal_key(it.key())[0] == b"k04"
    it.seek_to_last()
    assert split_internal_key(it.key())[0] == b"k09"


def test_iterator_stable_under_concurrent_insert():
    m = MemTable(ICMP)
    for i in range(0, 20, 2):
        m.add(i + 1, ValueType.VALUE, b"k%02d" % i, b"v")
    it = m.new_iterator()
    it.seek_to_first()
    seen = [split_internal_key(it.key())[0]]
    # Insert new keys while iterating; iterator must not skip/repeat.
    m.add(100, ValueType.VALUE, b"k01", b"new")
    it.next()
    seen.append(split_internal_key(it.key())[0])
    assert seen == [b"k00", b"k01"]


def test_hash_prefix_rep_matches_skiplist_semantics(tmp_path):
    """hash_skiplist rep (prefix-bucketed): same DB behavior as the default
    rep — ordered scans, reverse iteration, version visibility."""
    import random

    from toplingdb_tpu.db.db import DB
    from toplingdb_tpu.options import Options

    rng = random.Random(5)
    dumps = {}
    for rep in ("skiplist", "hash_skiplist"):
        d = str(tmp_path / rep)
        db = DB.open(d, Options(write_buffer_size=1 << 22, memtable_rep=rep,
                                disable_auto_compactions=True))
        model = {}
        for i in range(3000):
            k = b"key%05d" % rng.randrange(2000)
            if rng.random() < 0.85:
                v = b"v%05d" % i
                db.put(k, v); model[k] = v
            else:
                db.delete(k); model.pop(k, None)
        rng = random.Random(5)  # same sequence for both reps
        for k in (b"key00000", b"key01000", b"key01999", b"zzz"):
            assert db.get(k) == model.get(k)
        it = db.new_iterator()
        it.seek_to_first()
        fwd = list(it.entries())
        assert fwd == sorted(model.items())
        it2 = db.new_iterator()
        it2.seek_to_last()
        rev = []
        while it2.valid():
            rev.append((it2.key(), it2.value()))
            it2.prev()
        assert rev == fwd[::-1]
        it3 = db.new_iterator()
        it3.seek(b"key01000")
        assert it3.valid()
        dumps[rep] = fwd
        db.close()
    assert dumps["skiplist"] == dumps["hash_skiplist"]


def test_hash_prefix_rep_unit():
    from toplingdb_tpu.db.memtable import HashPrefixRep

    r = HashPrefixRep(prefix_len=3)
    import random

    rng = random.Random(1)
    keys = []
    for i in range(500):
        uk = b"%03d-%04d" % (rng.randrange(20), i)
        skey = (uk, rng.randrange(1 << 32))
        keys.append(skey)
        r.insert(skey, b"v%d" % i)
    assert len(r) == 500
    ordered = [k for k, _ in r.iter_all()]
    assert ordered == sorted(keys)
    # Cursor walk equals iter_all.
    walked = []
    pos = r.pos_first()
    while pos is not None:
        walked.append(r.entry_at(pos)[0])
        pos = r.pos_next(pos)
    assert walked == ordered
    # seek_ge / seek_lt on bucket boundaries.
    mid = sorted(keys)[250]
    assert r.entry_at(r.pos_seek_ge(mid))[0] == mid
    lt = r.pos_seek_lt(mid)
    assert r.entry_at(lt)[0] == sorted(keys)[249]
    assert r.pos_seek_lt(sorted(keys)[0]) is None
    assert r.pos_seek_ge((b"\xff\xff\xff\xff", 0)) is None
