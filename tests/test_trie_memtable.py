"""NativeTrieRep (the CSPP-role adaptive-radix memtable, reference
README.md:50 + memtablerep.h:309): full semantic parity with the skiplist
rep across random workloads, plus DB-level model checks."""

import random

import pytest

from toplingdb_tpu.db.memtable import NativeSkipListRep, NativeTrieRep


def _reps():
    try:
        return NativeSkipListRep(), NativeTrieRep()
    except RuntimeError:
        pytest.skip("native library unavailable")


def test_trie_matches_skiplist_random():
    a, b = _reps()
    rng = random.Random(3)
    keys = []
    for i in range(8000):
        klen = rng.choice([0, 1, 3, 8, 8, 20])
        uk = bytes(rng.randrange(256) for _ in range(klen))
        inv = rng.randrange(1 << 62)
        v = b"v%d" % i
        a.insert((uk, inv), v)
        b.insert((uk, inv), v)
        keys.append((uk, inv))
    assert len(a) == len(b)
    assert list(a.iter_all()) == list(b.iter_all())
    for _ in range(800):
        if rng.random() < 0.5:
            uk, inv = rng.choice(keys)
        else:
            uk = bytes(rng.randrange(256)
                       for _ in range(rng.choice([0, 2, 8])))
            inv = rng.randrange(1 << 62)
        for seek in ("pos_seek_ge", "pos_seek_lt"):
            pa = getattr(a, seek)((uk, inv))
            pb = getattr(b, seek)((uk, inv))
            ea = a.entry_at(pa) if pa else None
            eb = b.entry_at(pb) if pb else None
            assert ea == eb, (seek, uk, inv)
    # forward chain + last
    pa, pb = a.pos_first(), b.pos_first()
    for _ in range(200):
        ea = a.entry_at(pa) if pa else None
        eb = b.entry_at(pb) if pb else None
        assert ea == eb
        if pa is None:
            break
        pa, pb = a.pos_next(pa), b.pos_next(pb)
    assert a.entry_at(a.pos_last()) == b.entry_at(b.pos_last())


def test_trie_export_matches_skiplist():
    import numpy as np

    a, b = _reps()
    rng = random.Random(9)
    for i in range(5000):
        uk = b"k%06d" % rng.randrange(1500)
        inv = rng.randrange(1 << 60)
        a.insert((uk, inv), b"val%d" % i)
        b.insert((uk, inv), b"val%d" % i)
    ea, eb = a.export_columnar(), b.export_columnar()
    assert ea is not None and eb is not None
    assert np.array_equal(ea[0].key_buf, eb[0].key_buf)
    assert np.array_equal(ea[0].val_buf, eb[0].val_buf)
    assert np.array_equal(ea[1], eb[1])
    assert np.array_equal(ea[2], eb[2])


def test_trie_duplicate_replaces_in_place():
    _, b = _reps()
    b.insert((b"k", 42), b"v1")
    b.insert((b"k", 42), b"v2")  # WAL-replay duplicate
    assert len(b) == 1
    assert b.entry_at(b.pos_first()) == ((b"k", 42), b"v2")


def test_trie_db_model_check(tmp_path):
    from toplingdb_tpu.db.db import DB
    from toplingdb_tpu.options import Options

    try:
        NativeTrieRep()
    except RuntimeError:
        pytest.skip("native library unavailable")
    rng = random.Random(1)
    model = {}
    with DB.open(str(tmp_path / "db"),
                 Options(create_if_missing=True, memtable_rep="cspp",
                         write_buffer_size=256 * 1024)) as db:
        for i in range(15000):
            k = b"key%05d" % rng.randrange(4000)
            if rng.random() < 0.1:
                db.delete(k)
                model[k] = None
            else:
                v = b"val%d" % i
                db.put(k, v)
                model[k] = v
        db.flush()
        db.wait_for_compactions()
        for k, v in model.items():
            assert db.get(k) == v
        it = db.new_iterator()
        it.seek_to_first()
        got = []
        while it.valid():
            got.append(it.key())
            it.next()
        assert got == sorted(k for k, v in model.items() if v is not None)
    with DB.open(str(tmp_path / "db"),
                 Options(memtable_rep="cspp")) as db:
        for k, v in list(model.items())[:500]:
            assert db.get(k) == v
