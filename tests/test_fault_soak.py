"""Fault-injection soak (promoted from session soak testing; complements
the targeted fault tests): cycles of injected append/sync failures during
synced writes — every ACKNOWLEDGED write must survive the faults, resume,
and a clean reopen; failed writes must not corrupt anything."""

import random
import shutil
import tempfile

import pytest

from toplingdb_tpu.db.db import DB
from toplingdb_tpu.env import PosixEnv
from toplingdb_tpu.env.fault_injection import FaultInjectionEnv
from toplingdb_tpu.options import Options, WriteOptions


@pytest.mark.parametrize("seed", [0, 3])
def test_intermittent_io_faults_preserve_acknowledged_writes(seed):
    rng = random.Random(seed)
    fe = FaultInjectionEnv(PosixEnv())
    root = tempfile.mkdtemp(prefix=f"faultt{seed}_")
    d = root + "/db"
    db = DB.open(d, Options(write_buffer_size=8 * 1024,
                            level0_file_num_compaction_trigger=3), env=fe)
    model = {}
    wo = WriteOptions(sync=True)
    try:
        for cycle in range(6):
            for _ in range(rng.randrange(50, 200)):
                k = b"k%04d" % rng.randrange(500)
                v = b"v%06d" % rng.randrange(10 ** 6)
                db.put(k, v, wo)
                model[k] = v
            fe.fail_ops = {rng.choice(["append", "sync"])}
            for _ in range(rng.randrange(5, 30)):
                k = b"k%04d" % rng.randrange(500)
                v = b"F%06d" % rng.randrange(10 ** 6)
                try:
                    db.put(k, v, wo)
                    model[k] = v  # acknowledged despite faults
                except Exception:
                    pass          # rejected: must not take effect
            fe.fail_ops = set()
            try:
                db.resume()
            except Exception:
                pass
            db.wait_for_compactions()
            bad = [k for k, v in model.items() if db.get(k) != v]
            assert not bad, (cycle, bad[:3])
        db.close()
        with DB.open(d, Options()) as db2:  # reopen on the REAL env
            bad = [k for k, v in model.items() if db2.get(k) != v]
            assert not bad, bad[:3]
    finally:
        shutil.rmtree(root, ignore_errors=True)


# ===========================================================================
# dcompact chaos: injected worker failures must never change bytes on disk
# ===========================================================================

import hashlib
import os

from toplingdb_tpu.compaction.dcompact_service import (
    DcompactWorkerService,
    HttpCompactionExecutorFactory,
)
from toplingdb_tpu.compaction.executor import (
    SubprocessCompactionExecutorFactory,
)
from toplingdb_tpu.compaction.resilience import (
    DcompactFaultInjector,
    DcompactOptions,
)
from toplingdb_tpu.utils import statistics as st
from toplingdb_tpu.utils.statistics import Statistics

FROZEN_TIME = 1_700_000_000.0


def _freeze_time(monkeypatch):
    """Pin time.time() so SST properties (creation_time) are identical
    between a fault run and its no-fault twin; params carry the frozen
    stamp to workers. os mtimes (leases/heartbeats) stay real."""
    import time as _time

    monkeypatch.setattr(_time, "time", lambda: FROZEN_TIME)


def _bottom_level_hashes(db):
    """sha256 of every bottom-level SST, sorted — file NUMBERS may differ
    between runs (failed attempts burn different counters), bytes must
    not."""
    from toplingdb_tpu.db import filename as fn

    v = db.versions.cf_current(0)
    out = []
    for f in v.files[v.num_levels - 1]:
        p = fn.table_file_name(db.dbname, f.number)
        out.append(hashlib.sha256(open(p, "rb").read()).hexdigest())
    return sorted(out)


def _chaos_policy(**kw):
    base = dict(max_attempts=3, backoff_base=0.005, backoff_jitter=0.1,
                attempt_timeout=120.0, breaker_failure_threshold=2,
                breaker_reset_timeout=0.15, local_pin_failures=10 ** 6,
                lease_sec=5.0)
    base.update(kw)
    return DcompactOptions(**base)


def _run_matrix_workload(root, factory, stats):
    opts = Options(write_buffer_size=1 << 14, disable_auto_compactions=True,
                   compaction_executor_factory=factory, statistics=stats,
                   dcompact=getattr(factory, "policy", None))
    db = DB.open(root, opts)
    model = {}
    for i in range(1600):
        k = b"mk%05d" % (i % 500)
        v = b"mv%07d" % i
        db.put(k, v)
        model[k] = v
        if i % 400 == 399:
            db.flush()
    db.flush()
    db.compact_range()
    assert db._bg_error is None
    bad = [k for k, v in model.items() if db.get(k) != v]
    assert not bad, bad[:3]
    hashes = _bottom_level_hashes(db)
    db.close()
    return hashes


@pytest.mark.parametrize("plan", ["drop", "kill", "truncate", "corrupt",
                                  "delay"])
def test_dcompact_chaos_matrix_byte_parity(tmp_path, monkeypatch, plan):
    """Chaos matrix over the HTTP transport: request dropped, worker
    killed mid-job, results truncated, results corrupted, response
    delayed. Every faulted run must end byte-identical to the no-fault
    twin, with the failure attributed as a retry (delay alone succeeds
    first try)."""
    _freeze_time(monkeypatch)
    svc = DcompactWorkerService(device="cpu")
    port = svc.start()
    try:
        clean_stats = Statistics()
        clean = _run_matrix_workload(
            str(tmp_path / "clean"),
            HttpCompactionExecutorFactory([f"http://127.0.0.1:{port}"],
                                          policy=_chaos_policy()),
            clean_stats)

        stats = Statistics()
        inj = DcompactFaultInjector(schedule={0: plan}, delay_sec=0.05)
        fac = HttpCompactionExecutorFactory(
            [f"http://127.0.0.1:{port}"], policy=_chaos_policy(),
            fault_injector=inj)
        faulty = _run_matrix_workload(str(tmp_path / "fault"), fac, stats)

        assert faulty == clean and clean, (plan, clean, faulty)
        t = stats.tickers()
        if plan == "delay":
            assert t.get(st.DCOMPACTION_RETRIES, 0) == 0
        else:
            assert t.get(st.DCOMPACTION_RETRIES, 0) == 1
            assert t[st.DCOMPACTION_ATTEMPTS] == \
                clean_stats.tickers()[st.DCOMPACTION_ATTEMPTS] + 1
        assert t.get(st.DCOMPACTION_JOB_FAILURES, 0) == 0
        assert t.get(st.DCOMPACTION_FALLBACK_LOCAL, 0) == 0
    finally:
        svc.stop()


def test_dcompact_worker_kill_9_subprocess_retries(tmp_path, monkeypatch):
    """REAL process death: the worker subprocess os._exit(137)s mid-job
    (heartbeat written, partial output on disk, no results.json). The
    attempt's partial state is swept, the retry succeeds, bytes match the
    no-fault twin."""
    _freeze_time(monkeypatch)
    clean = _run_matrix_workload(
        str(tmp_path / "clean"),
        SubprocessCompactionExecutorFactory(device="cpu",
                                            policy=_chaos_policy()),
        Statistics())
    stats = Statistics()
    inj = DcompactFaultInjector(schedule={0: "kill"})
    faulty = _run_matrix_workload(
        str(tmp_path / "fault"),
        SubprocessCompactionExecutorFactory(
            device="cpu", policy=_chaos_policy(), fault_injector=inj),
        stats)
    assert faulty == clean and clean
    t = stats.tickers()
    assert t.get(st.DCOMPACTION_RETRIES, 0) == 1
    assert inj.injected_counts() == {"kill": 1}
    # The killed attempt left no residue behind (swept on failure).
    dc = str(tmp_path / "fault" / "dcompact")
    leftovers = []
    for r, _d, fs in os.walk(dc):
        leftovers += [os.path.join(r, f) for f in fs]
    assert leftovers == [], leftovers


def test_dcompact_chaos_soak_30pct_byte_parity(tmp_path, monkeypatch):
    """Acceptance: a real DB under write load with auto compactions
    against a flaky two-worker dcompact fleet failing ~30% of attempts
    (drop/kill/truncate/corrupt) finishes the workload with bottom-level
    SSTs byte-identical to a no-fault run, zero background-error
    escalation, and every failed attempt attributed in DCOMPACTION_*
    statistics."""
    _freeze_time(monkeypatch)

    def soak(root, services, injector, stats):
        urls = [f"http://127.0.0.1:{p}" for p in
                (s.start() for s in services)]
        policy = _chaos_policy()
        fac = HttpCompactionExecutorFactory(
            urls, policy=policy, fault_injector=injector)
        opts = Options(write_buffer_size=1 << 14,
                       level0_file_num_compaction_trigger=2,
                       max_background_jobs=2,
                       compaction_executor_factory=fac, statistics=stats,
                       dcompact=policy)
        db = DB.open(root, opts)
        model = {}
        for i in range(6000):
            k = b"sk%05d" % (i % 700)
            v = b"sv%07d" % i
            db.put(k, v)
            model[k] = v
            if i % 500 == 499:
                db.flush()
        db.flush()
        db.wait_for_compactions()
        db.compact_range()
        assert db._bg_error is None, db._bg_error  # no HARD/FATAL escalation
        bad = [k for k, v in model.items() if db.get(k) != v]
        assert not bad, bad[:3]
        hashes = _bottom_level_hashes(db)
        db.close()
        for s in services:
            s.stop()
        return hashes

    clean = soak(str(tmp_path / "clean"),
                 [DcompactWorkerService(device="cpu") for _ in range(2)],
                 None, Statistics())

    # ~30% of attempts fail; the first three ordinals are forced so the
    # structural outcomes are guaranteed regardless of background timing:
    # job 1 fails all 3 attempts (-> local fallback + job failure), and
    # with two URLs round-robin its attempts land A,B,A — two consecutive
    # failures on A open A's breaker (threshold 2).
    inj = DcompactFaultInjector(
        schedule={0: "drop", 1: "drop", 2: "drop"},
        rate=0.3, plans=("drop", "kill", "truncate", "corrupt"), seed=1234)
    stats = Statistics()
    faulty = soak(str(tmp_path / "fault"),
                  [DcompactWorkerService(device="cpu") for _ in range(2)],
                  inj, stats)

    assert faulty == clean and clean, (clean, faulty)
    t = stats.tickers()
    n_injected = sum(inj.injected_counts().values())
    assert n_injected >= 3
    # Every injected fault surfaced as exactly one failed attempt, and
    # every failed attempt is attributed: it either retried or exhausted
    # its job.
    assert t.get(st.DCOMPACTION_RETRIES, 0) > 0
    assert t.get(st.DCOMPACTION_FALLBACK_LOCAL, 0) > 0
    assert t.get(st.DCOMPACTION_BREAKER_OPEN, 0) > 0
    assert t[st.DCOMPACTION_RETRIES] + t[st.DCOMPACTION_JOB_FAILURES] \
        == n_injected
    assert t[st.DCOMPACTION_FALLBACK_LOCAL] == \
        t[st.DCOMPACTION_JOB_FAILURES] + \
        t.get(st.DCOMPACTION_BREAKER_SKIPPED, 0) + \
        t.get(st.DCOMPACTION_DEADLINE_EXCEEDED, 0)
    assert stats.get_histogram(st.DCOMPACTION_ATTEMPT_MICROS).count == \
        t[st.DCOMPACTION_ATTEMPTS]
