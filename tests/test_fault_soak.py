"""Fault-injection soak (promoted from session soak testing; complements
the targeted fault tests): cycles of injected append/sync failures during
synced writes — every ACKNOWLEDGED write must survive the faults, resume,
and a clean reopen; failed writes must not corrupt anything."""

import random
import shutil
import tempfile

import pytest

from toplingdb_tpu.db.db import DB
from toplingdb_tpu.env import PosixEnv
from toplingdb_tpu.env.fault_injection import FaultInjectionEnv
from toplingdb_tpu.options import Options, WriteOptions


@pytest.mark.parametrize("seed", [0, 3])
def test_intermittent_io_faults_preserve_acknowledged_writes(seed):
    rng = random.Random(seed)
    fe = FaultInjectionEnv(PosixEnv())
    root = tempfile.mkdtemp(prefix=f"faultt{seed}_")
    d = root + "/db"
    db = DB.open(d, Options(write_buffer_size=8 * 1024,
                            level0_file_num_compaction_trigger=3), env=fe)
    model = {}
    wo = WriteOptions(sync=True)
    try:
        for cycle in range(6):
            for _ in range(rng.randrange(50, 200)):
                k = b"k%04d" % rng.randrange(500)
                v = b"v%06d" % rng.randrange(10 ** 6)
                db.put(k, v, wo)
                model[k] = v
            fe.fail_ops = {rng.choice(["append", "sync"])}
            for _ in range(rng.randrange(5, 30)):
                k = b"k%04d" % rng.randrange(500)
                v = b"F%06d" % rng.randrange(10 ** 6)
                try:
                    db.put(k, v, wo)
                    model[k] = v  # acknowledged despite faults
                except Exception:
                    pass          # rejected: must not take effect
            fe.fail_ops = set()
            try:
                db.resume()
            except Exception:
                pass
            db.wait_for_compactions()
            bad = [k for k, v in model.items() if db.get(k) != v]
            assert not bad, (cycle, bad[:3])
        db.close()
        with DB.open(d, Options()) as db2:  # reopen on the REAL env
            bad = [k for k, v in model.items() if db2.get(k) != v]
            assert not bad, bad[:3]
    finally:
        shutil.rmtree(root, ignore_errors=True)
