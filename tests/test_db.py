import struct

import pytest

from toplingdb_tpu.db.db import DB
from toplingdb_tpu.options import FlushOptions, Options, ReadOptions, WriteOptions
from toplingdb_tpu.utils.merge_operator import StringAppendOperator, UInt64AddOperator
from toplingdb_tpu.utils.status import InvalidArgument


def opts(**kw):
    kw.setdefault("write_buffer_size", 32 * 1024)
    return Options(**kw)


def test_open_put_get_close_reopen(tmp_db_path):
    with DB.open(tmp_db_path, opts()) as db:
        db.put(b"a", b"1")
        db.put(b"b", b"2")
        assert db.get(b"a") == b"1"
        assert db.get(b"missing") is None
    with DB.open(tmp_db_path, opts()) as db:
        assert db.get(b"a") == b"1"
        assert db.get(b"b") == b"2"


def test_create_if_missing_false(tmp_db_path):
    with pytest.raises(InvalidArgument):
        DB.open(tmp_db_path, opts(create_if_missing=False))


def test_error_if_exists(tmp_db_path):
    DB.open(tmp_db_path, opts()).close()
    with pytest.raises(InvalidArgument):
        DB.open(tmp_db_path, opts(error_if_exists=True))


def test_overwrite_and_delete(tmp_db_path):
    with DB.open(tmp_db_path, opts()) as db:
        db.put(b"k", b"v1")
        db.put(b"k", b"v2")
        assert db.get(b"k") == b"v2"
        db.delete(b"k")
        assert db.get(b"k") is None
        db.put(b"k", b"v3")
        assert db.get(b"k") == b"v3"


def test_flush_and_read_from_sst(tmp_db_path):
    with DB.open(tmp_db_path, opts()) as db:
        for i in range(100):
            db.put(b"key%04d" % i, b"val%04d" % i)
        db.flush()
        assert db.mem.empty()
        assert len(db.versions.current.files[0]) >= 1
        assert db.get(b"key0050") == b"val0050"
        db.delete(b"key0050")
        db.flush()
        assert db.get(b"key0050") is None  # tombstone in newer L0 file


def test_recovery_replays_wal(tmp_db_path):
    db = DB.open(tmp_db_path, opts())
    db.put(b"durable", b"yes", WriteOptions(sync=True))
    # Simulate crash: drop the handle without close() (no flush).
    db._closed = True
    db2 = DB.open(tmp_db_path, opts())
    assert db2.get(b"durable") == b"yes"
    db2.close()


def test_auto_flush_on_write_buffer_full(tmp_db_path):
    with DB.open(tmp_db_path, opts(write_buffer_size=8 * 1024)) as db:
        for i in range(2000):
            db.put(b"key%06d" % i, b"x" * 30)
        db.wait_for_compactions()
        assert db.versions.current.num_files() > 0
        assert db.get(b"key000000") == b"x" * 30
        assert db.get(b"key001999") == b"x" * 30


def test_snapshot_isolation(tmp_db_path):
    with DB.open(tmp_db_path, opts()) as db:
        db.put(b"k", b"old")
        snap = db.get_snapshot()
        db.put(b"k", b"new")
        db.delete(b"k2")
        assert db.get(b"k", ReadOptions(snapshot=snap)) == b"old"
        assert db.get(b"k") == b"new"
        # Snapshot survives flush.
        db.flush()
        assert db.get(b"k", ReadOptions(snapshot=snap)) == b"old"
        snap.release()


def test_merge_operator(tmp_db_path):
    with DB.open(tmp_db_path, opts(merge_operator=UInt64AddOperator())) as db:
        db.merge(b"c", struct.pack("<Q", 1))
        db.merge(b"c", struct.pack("<Q", 2))
        assert struct.unpack("<Q", db.get(b"c"))[0] == 3
        db.flush()
        db.merge(b"c", struct.pack("<Q", 10))  # operand in mem, base in SST
        assert struct.unpack("<Q", db.get(b"c"))[0] == 13
        db.put(b"c", struct.pack("<Q", 100))   # put resets the chain
        db.merge(b"c", struct.pack("<Q", 1))
        assert struct.unpack("<Q", db.get(b"c"))[0] == 101


def test_merge_across_flush_with_delete(tmp_db_path):
    with DB.open(tmp_db_path, opts(merge_operator=StringAppendOperator())) as db:
        db.put(b"s", b"base")
        db.flush()
        db.delete(b"s")
        db.merge(b"s", b"x")
        db.merge(b"s", b"y")
        assert db.get(b"s") == b"x,y"  # delete cuts the chain from base


def test_delete_range(tmp_db_path):
    with DB.open(tmp_db_path, opts()) as db:
        for i in range(100):
            db.put(b"key%03d" % i, b"v")
        db.delete_range(b"key020", b"key040")
        assert db.get(b"key019") == b"v"
        assert db.get(b"key020") is None
        assert db.get(b"key039") is None
        assert db.get(b"key040") == b"v"
        # Writes after the tombstone are visible.
        db.put(b"key025", b"back")
        assert db.get(b"key025") == b"back"
        # Survives flush and reopen.
        db.flush()
        assert db.get(b"key030") is None
    with DB.open(tmp_db_path, opts()) as db:
        assert db.get(b"key030") is None
        assert db.get(b"key025") == b"back"


def test_write_batch_atomic(tmp_db_path):
    from toplingdb_tpu.db.write_batch import WriteBatch

    with DB.open(tmp_db_path, opts()) as db:
        b = WriteBatch()
        b.put(b"a", b"1")
        b.put(b"b", b"2")
        b.delete(b"a")
        db.write(b)
        assert db.get(b"a") is None
        assert db.get(b"b") == b"2"


def test_reopen_after_many_flushes(tmp_db_path):
    expected = {}
    for round_ in range(3):
        with DB.open(tmp_db_path, opts()) as db:
            for i in range(50):
                k = b"key%03d" % (round_ * 50 + i)
                v = b"r%d" % round_
                db.put(k, v)
                expected[k] = v
            db.flush()
    with DB.open(tmp_db_path, opts()) as db:
        for k, v in expected.items():
            assert db.get(k) == v, k


def test_get_property(tmp_db_path):
    with DB.open(tmp_db_path, opts()) as db:
        db.put(b"a", b"1")
        db.flush()
        assert "L0: 1 files" in db.get_property("tpulsm.stats")
        assert db.get_property("tpulsm.num-files-at-level0") == "1"


def test_blob_files(tmp_db_path):
    """Key-value separation: big values go to .blob files; reads resolve
    transparently through get, iterators, compaction, and reopen."""
    import os

    with DB.open(tmp_db_path, opts(enable_blob_files=True, min_blob_size=100)) as db:
        small = b"s" * 10
        big = b"B" * 5000
        for i in range(200):
            db.put(b"key%03d" % i, big if i % 2 else small)
        db.flush()
        assert any(f.endswith(".blob") for f in os.listdir(tmp_db_path))
        assert db.get(b"key001") == big
        assert db.get(b"key002") == small
        it = db.new_iterator()
        it.seek_to_first()
        vals = [v for _, v in it.entries()]
        assert vals[1] == big and vals[2] == small
        # SSTs must be small (values separated).
        sst_bytes = sum(
            os.path.getsize(f"{tmp_db_path}/{f}")
            for f in os.listdir(tmp_db_path) if f.endswith(".sst")
        )
        assert sst_bytes < 100 * 5000 / 4
        db.compact_range()  # blob indexes pass through compaction
        assert db.get(b"key199") == big
    with DB.open(tmp_db_path, opts(enable_blob_files=True, min_blob_size=100)) as db:
        assert db.get(b"key001") == b"B" * 5000
        assert db.get(b"key002") == b"s" * 10


def test_blob_merge_resolves_base(tmp_db_path):
    """Review regression: merge over a blob-separated base must fold the
    REAL value, not the raw blob index bytes."""
    with DB.open(tmp_db_path, opts(enable_blob_files=True, min_blob_size=100,
                                   merge_operator=StringAppendOperator())) as db:
        big = b"B" * 500
        db.put(b"k", big)
        db.flush()                     # value becomes BLOB_INDEX
        db.merge(b"k", b"tail")
        db.flush()
        db.compact_range()
        assert db.get(b"k") == big + b",tail"
    with DB.open(tmp_db_path, opts(enable_blob_files=True, min_blob_size=100,
                                   merge_operator=StringAppendOperator())) as db:
        assert db.get(b"k") == b"B" * 500 + b",tail"


def test_checkpoint_includes_blob_files(tmp_db_path, tmp_path):
    """Review regression: checkpoints of blob-enabled DBs must be openable."""
    from toplingdb_tpu.utilities.checkpoint import create_checkpoint

    dst = str(tmp_path / "ckpt")
    with DB.open(tmp_db_path, opts(enable_blob_files=True, min_blob_size=100)) as db:
        db.put(b"k", b"B" * 500)
        db.flush()
        create_checkpoint(db, dst)
    with DB.open(dst, opts(enable_blob_files=True, min_blob_size=100)) as db2:
        assert db2.get(b"k") == b"B" * 500


def test_blob_min_size_zero_separates_everything(tmp_db_path):
    import os

    with DB.open(tmp_db_path, opts(enable_blob_files=True, min_blob_size=0)) as db:
        db.put(b"k", b"tiny")
        db.flush()
        assert any(f.endswith(".blob") for f in os.listdir(tmp_db_path))
        assert db.get(b"k") == b"tiny"


def test_wide_column_magic_collision(tmp_db_path):
    from toplingdb_tpu.db.wide_columns import DEFAULT_COLUMN, get_entity

    with DB.open(tmp_db_path, opts()) as db:
        tricky = b"\x00WCE1" + b"\xff\xfe arbitrary binary"
        db.put(b"k", tricky)
        e = get_entity(db, b"k")
        # Must fall back to the default-column view, not raise.
        assert e == {DEFAULT_COLUMN: tricky} or DEFAULT_COLUMN not in e


def test_multi_get_batched(tmp_db_path):
    with DB.open(tmp_db_path, opts(write_buffer_size=8 * 1024)) as db:
        for i in range(2000):
            db.put(b"key%05d" % (i % 600), b"v%07d" % i)
        db.flush()
        db.delete(b"key00005")
        db.delete_range(b"key00100", b"key00110")
        keys = [b"key%05d" % k for k in range(0, 600, 7)] + [b"missing", b"key00005", b"key00105"]
        got = db.multi_get(keys)
        want = [db.get(k) for k in keys]
        assert got == want
        assert db.multi_get([]) == []


def test_multi_get_newest_version_across_levels(tmp_db_path):
    """A key with its newest version in L0 and older versions deeper must not
    be resolved from the deeper file first."""
    with DB.open(tmp_db_path, opts(disable_auto_compactions=True)) as db:
        db.put(b"k", b"old")
        db.put(b"other", b"x")
        db.flush()
        db.compact_range()          # old version now at the bottom level
        db.put(b"k", b"new")
        db.flush()                  # new version in L0
        assert db.multi_get([b"k", b"other"]) == [b"new", b"x"]


def test_write_stall_on_l0_pileup(tmp_db_path):
    with DB.open(tmp_db_path, opts(
        write_buffer_size=4 * 1024, disable_auto_compactions=True,
    )) as db:
        import time

        for r in range(5):
            for i in range(100):
                db.put(b"k%05d" % (r * 100 + i), b"x" * 30)
            db.flush()
        assert len(db.versions.current.files[0]) >= 5
        # Stalls are a no-op while compaction is disabled (bulk-load mode).
        t0 = time.monotonic()
        db._maybe_stall_writes(timeout=1.0)
        assert time.monotonic() - t0 < 0.2
        # Enable compaction and lower the triggers: the stall must hold until
        # L0 drains below the stop trigger (or the timeout).
        db.options.level0_slowdown_writes_trigger = 2
        db.options.level0_stop_writes_trigger = 4
        db.options.disable_auto_compactions = False
        t0 = time.monotonic()
        db._maybe_stall_writes(timeout=3.0)
        dt = time.monotonic() - t0
        assert db._max_l0_files() < 4 or dt >= 3.0
        db.wait_for_compactions()


def test_repair_db(tmp_db_path):
    from toplingdb_tpu.db.repair import repair_db

    with DB.open(tmp_db_path, opts(write_buffer_size=8 * 1024)) as db:
        for i in range(1500):
            db.put(b"key%05d" % i, b"v%05d" % i)
        db.flush()
        db.put(b"wal-only", b"yes")
        db._wal.sync()
        db._closed = True  # crash
    import os

    # Destroy the MANIFEST entirely.
    for f in os.listdir(tmp_db_path):
        if f.startswith("MANIFEST") or f == "CURRENT":
            os.remove(f"{tmp_db_path}/{f}")
    report = repair_db(tmp_db_path, opts())
    assert report["tables_kept"] >= 1
    with DB.open(tmp_db_path, opts()) as db:
        assert db.get(b"key00750") == b"v00750"
        assert db.get(b"wal-only") == b"yes"


def test_group_commit_concurrent_writers(tmp_db_path):
    """Many threads write concurrently; the leader/follower protocol must
    apply every batch exactly once with distinct sequences (reference
    WriteThread::JoinBatchGroup semantics)."""
    import threading

    n_threads, per_thread = 8, 50
    with DB.open(tmp_db_path, opts(write_buffer_size=1 << 20)) as db:
        errs = []

        def writer(tid):
            try:
                for i in range(per_thread):
                    db.put(f"t{tid:02d}-{i:04d}".encode(), f"v{tid}.{i}".encode())
            except BaseException as e:  # noqa: BLE001
                errs.append(e)

        threads = [threading.Thread(target=writer, args=(t,))
                   for t in range(n_threads)]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        assert not errs
        assert db.versions.last_sequence == n_threads * per_thread
        for tid in range(n_threads):
            for i in range(per_thread):
                assert db.get(f"t{tid:02d}-{i:04d}".encode()) == \
                    f"v{tid}.{i}".encode()
    # Durability: every write must be replayable from the merged WAL records.
    with DB.open(tmp_db_path, opts()) as db:
        assert db.get(b"t00-0000") == b"v0.0"
        assert db.get(b"t07-0049") == b"v7.49"


def test_group_commit_merges_queued_followers(tmp_db_path):
    """While the leader is stuck inside the WAL append, followers queue up;
    the next leader must commit them as ONE merged WAL record."""
    import threading
    import time

    with DB.open(tmp_db_path, opts()) as db:
        wal = db._wal
        real_add = wal.add_record
        records = []
        gate = threading.Event()

        def slow_add(data):
            records.append(data)
            if len(records) == 1:
                gate.wait(5.0)  # hold the leader so followers pile up
            real_add(data)

        wal.add_record = slow_add
        t0 = threading.Thread(target=db.put, args=(b"lead", b"0"))
        t0.start()
        while not records:
            time.sleep(0.001)
        followers = [
            threading.Thread(target=db.put, args=(f"f{i}".encode(), b"x"))
            for i in range(4)
        ]
        for t in followers:
            t.start()
        time.sleep(0.05)  # let followers enqueue behind the stuck leader
        gate.set()
        t0.join()
        for t in followers:
            t.join()
        # Leader's record + one merged record for the queued followers.
        assert len(records) == 2
        from toplingdb_tpu.db.write_batch import WriteBatch

        merged = WriteBatch(records[1])
        assert merged.count() == 4
        for i in range(4):
            assert db.get(f"f{i}".encode()) == b"x"


def _blob_files(db):
    from toplingdb_tpu.db import filename as fn

    return sorted(
        num for child in db.env.get_children(db.dbname)
        for t, num in [fn.parse_file_name(child)] if t == fn.FileType.BLOB
    )


def test_blob_refs_tracked_and_unreferenced_blob_deleted(tmp_db_path):
    """FileMetaData.blob_refs keeps referenced blob files alive; once every
    referencing SST is compacted away, the blob file is GC'd."""
    o = opts(enable_blob_files=True, min_blob_size=10,
             disable_auto_compactions=True)
    with DB.open(tmp_db_path, o) as db:
        db.put(b"k1", b"B" * 100)
        db.flush()
        assert db.versions.current.files[0][0].blob_refs, \
            "flush must record the blob ref"
        assert len(_blob_files(db)) == 1
        # Overwrite with a small value, then compact to the bottom: the old
        # blob entry is superseded, no SST references the blob file anymore.
        db.put(b"k1", b"small")
        db.flush()
        db.compact_range()
        assert db.get(b"k1") == b"small"
        assert _blob_files(db) == [], "unreferenced blob file must be deleted"
    with DB.open(tmp_db_path, o) as db:
        assert db.get(b"k1") == b"small"


def test_blob_refs_survive_reopen_and_passthrough_compaction(tmp_db_path):
    o = opts(enable_blob_files=True, min_blob_size=10,
             disable_auto_compactions=True)
    with DB.open(tmp_db_path, o) as db:
        for i in range(5):
            db.put(f"k{i}".encode(), f"V{i}".encode() * 20)
        db.flush()
        refs0 = db.versions.current.files[0][0].blob_refs
        assert refs0
    with DB.open(tmp_db_path, o) as db:  # MANIFEST round-trip
        assert db.versions.current.files[0][0].blob_refs == refs0
        db.compact_range()  # passthrough: output SST must carry the refs
        files = [f for lvl in db.versions.current.files for f in lvl]
        assert len(files) == 1
        assert files[0].blob_refs == refs0
        assert len(_blob_files(db)) == 1
        for i in range(5):
            assert db.get(f"k{i}".encode()) == f"V{i}".encode() * 20


def test_blob_garbage_collection_rewrites_old_files(tmp_db_path):
    """With GC enabled at cutoff 1.0, compaction rewrites every surviving
    blob out of the aged files, which are then deleted."""
    o = opts(enable_blob_files=True, min_blob_size=10,
             enable_blob_garbage_collection=True,
             blob_garbage_collection_age_cutoff=1.0,
             disable_auto_compactions=True)
    with DB.open(tmp_db_path, o) as db:
        for i in range(4):
            db.put(f"a{i}".encode(), f"X{i}".encode() * 30)
        db.flush()
        for i in range(4):
            db.put(f"b{i}".encode(), f"Y{i}".encode() * 30)
        db.flush()
        old = _blob_files(db)
        assert len(old) == 2
        db.compact_range()
        new = _blob_files(db)
        assert len(new) == 1 and new[0] not in old, \
            "survivors must move to ONE fresh blob file; aged files deleted"
        for i in range(4):
            assert db.get(f"a{i}".encode()) == f"X{i}".encode() * 30
            assert db.get(f"b{i}".encode()) == f"Y{i}".encode() * 30
    with DB.open(tmp_db_path, o) as db:
        assert db.get(b"a0") == b"X0" * 30


def test_blob_gc_inlines_small_survivors(tmp_db_path):
    """A GC'd blob whose value now sits under min_blob_size is inlined back
    into the SST (type flips BLOB_INDEX → VALUE)."""
    o = opts(enable_blob_files=True, min_blob_size=10,
             disable_auto_compactions=True)
    with DB.open(tmp_db_path, o) as db:
        db.put(b"k", b"Z" * 50)
        db.flush()
    # Reopen with a bigger min_blob_size: at GC time the 50B value is below
    # the new 100B threshold, so it must be inlined.
    o2 = opts(enable_blob_files=True, min_blob_size=100,
              enable_blob_garbage_collection=True,
              blob_garbage_collection_age_cutoff=1.0,
              disable_auto_compactions=True)
    with DB.open(tmp_db_path, o2) as db:
        db.compact_range()
        assert db.get(b"k") == b"Z" * 50
        assert _blob_files(db) == []
        files = [f for lvl in db.versions.current.files for f in lvl]
        assert all(not f.blob_refs for f in files)


def test_repair_db_multi_cf(tmp_db_path):
    """Repair reconstructs column families from table properties and WAL
    CF-prefixed records (reference db/repair.cc keeps CFs too)."""
    import os

    from toplingdb_tpu.db.repair import repair_db

    with DB.open(tmp_db_path, opts()) as db:
        cf = db.create_column_family("meta")
        db.put(b"dk", b"dv")
        db.put(b"mk", b"mv", cf=cf)
        db.flush()
        db.put(b"wal-d", b"1")
        db.put(b"wal-m", b"2", cf=cf)
        db._wal.sync()
        db._closed = True  # crash
    for f in os.listdir(tmp_db_path):
        if f.startswith("MANIFEST") or f == "CURRENT":
            os.remove(f"{tmp_db_path}/{f}")
    report = repair_db(tmp_db_path, opts())
    assert "meta" in report["column_families"].values()
    with DB.open(tmp_db_path, opts()) as db:
        cf = db.get_column_family("meta")
        assert cf is not None
        assert db.get(b"dk") == b"dv"
        assert db.get(b"mk", cf=cf) == b"mv"
        assert db.get(b"wal-d") == b"1"
        assert db.get(b"wal-m", cf=cf) == b"2"
        assert db.get(b"mk") is None, "CF data must not leak into default"


def test_write_buffer_manager_across_dbs(tmp_path):
    """A shared WriteBufferManager budget forces early flushes across DB
    instances and tracks usage (reference write_buffer_manager.h:37)."""
    from toplingdb_tpu.utils.rate_limiter import WriteBufferManager

    wbm = WriteBufferManager(24 * 1024)
    o1 = opts(write_buffer_size=1 << 26, write_buffer_manager=wbm)
    o2 = opts(write_buffer_size=1 << 26, write_buffer_manager=wbm)
    with DB.open(str(tmp_path / "db1"), o1) as db1, \
            DB.open(str(tmp_path / "db2"), o2) as db2:
        for i in range(400):
            db1.put(b"a%04d" % i, b"x" * 40)
            db2.put(b"b%04d" % i, b"y" * 40)
        # Per-DB write_buffer_size (64MiB) would never flush; the shared
        # 24KiB budget must have.
        flushed = (db1.versions.current.num_files()
                   + db2.versions.current.num_files())
        assert flushed > 0, "shared budget never triggered a flush"
        assert wbm.memory_usage() <= 64 * 1024
        assert db1.get(b"a0000") == b"x" * 40
        assert db2.get(b"b0399") == b"y" * 40
        # Manual flush must release the charge too (not only close). A
        # small residual is the fresh empty memtables' head allocations —
        # physical accounting charges those (reference WBM counts arena
        # blocks of empty memtables too).
        db1.flush()
        db2.flush()
        assert wbm.memory_usage() < 4096, \
            "flush must release the DB's data charge"
    assert wbm.memory_usage() == 0, "close must release the DB's charge"


def test_verify_checksum_detects_corruption(tmp_db_path):
    import os

    from toplingdb_tpu.utils.status import Corruption

    with DB.open(tmp_db_path, opts(disable_auto_compactions=True)) as db:
        for i in range(500):
            db.put(b"k%04d" % i, b"v" * 40)
        db.flush()
        db.verify_checksum()  # clean pass
        f = db.versions.current.files[0][0]
        path = f"{tmp_db_path}/{f.number:06d}.sst"
        db.table_cache.evict(f.number)
        data = bytearray(open(path, "rb").read())
        data[len(data) // 3] ^= 0xFF  # flip a data-block byte
        open(path, "wb").write(bytes(data))
        with pytest.raises(Corruption):
            db.verify_checksum()
        db._closed = True  # skip close-flush against the corrupt file


def test_get_approximate_sizes(tmp_db_path):
    with DB.open(tmp_db_path, opts(disable_auto_compactions=True)) as db:
        for i in range(3000):
            db.put(b"key%05d" % i, b"v" * 64)
        db.flush()
        sizes = db.get_approximate_sizes(
            [(b"key00000", b"key03000"), (b"key01000", b"key01100"),
             (b"zz", b"zzz")]
        )
        assert sizes[0] > sizes[1] > 0
        assert sizes[2] == 0
        total = sum(f.file_size for _, f in db.versions.current.all_files())
        assert sizes[0] <= total * 1.2


def test_delete_files_in_range(tmp_db_path):
    with DB.open(tmp_db_path, opts(write_buffer_size=8 * 1024,
                                   target_file_size_base=16 * 1024,
                                   disable_auto_compactions=True)) as db:
        for i in range(4000):
            db.put(b"key%05d" % i, b"x" * 40)
        db.flush()
        db.compact_range()  # push everything to L1+ (multiple files)
        v = db.versions.current
        n_before = v.num_files()
        assert n_before > 2
        dropped = db.delete_files_in_range(b"key00500", b"key03500")
        assert dropped > 0
        # Fully-contained ranges are gone; boundary data survives.
        assert db.get(b"key00000") is not None
        assert db.get(b"key03999") is not None
        assert db.versions.current.num_files() == n_before - dropped
    with DB.open(tmp_db_path, opts()) as db:
        assert db.get(b"key00000") is not None


def test_pause_continue_background_work(tmp_db_path):
    with DB.open(tmp_db_path, opts(write_buffer_size=4 * 1024,
                                   level0_file_num_compaction_trigger=2)) as db:
        db.pause_background_work()
        for i in range(600):
            db.put(b"key%05d" % i, b"x" * 30)
        n_l0 = len(db.versions.current.files[0])
        assert n_l0 >= 2, "L0 should pile up while paused"
        db.continue_background_work()
        db.wait_for_compactions()
        assert db.get(b"key00001") == b"x" * 30


def test_block_cache_tracer(tmp_db_path, tmp_path):
    from toplingdb_tpu.utils.cache import (
        BlockCacheTracer, LRUCache, analyze_block_cache_trace,
    )

    trace = str(tmp_path / "bc.trace")
    tracer = BlockCacheTracer(trace)
    o = opts(disable_auto_compactions=True,
             block_cache=LRUCache(1 << 20, tracer=tracer))
    with DB.open(tmp_db_path, o) as db:
        for i in range(1000):
            db.put(b"k%04d" % i, b"v" * 30)
        db.flush()
        for _ in range(3):
            assert db.get(b"k0500") == b"v" * 30
    tracer.close()
    agg = analyze_block_cache_trace(trace)
    assert agg["hits"] + agg["misses"] > 0
    assert agg["hits"] > 0, "repeat reads must hit the cache"


def test_extended_properties(tmp_db_path):
    with DB.open(tmp_db_path, opts(disable_auto_compactions=True)) as db:
        for i in range(200):
            db.put(b"k%04d" % i, b"v")
        db.flush()
        for i in range(100, 300):
            db.put(b"k%04d" % i, b"v")
        snap = db.get_snapshot()
        assert int(db.get_property("tpulsm.estimate-num-keys")) >= 200
        assert int(db.get_property("tpulsm.cur-size-all-mem-tables")) > 0
        assert db.get_property("tpulsm.num-snapshots") == "1"
        assert int(db.get_property("tpulsm.estimate-live-data-size")) > 0
        assert db.get_property("tpulsm.background-errors") == "0"
        assert db.get_property("tpulsm.num-running-compactions") == "0"
        snap.release()


def test_get_merge_operands(tmp_db_path):
    with DB.open(tmp_db_path, opts(merge_operator=StringAppendOperator())) as db:
        db.put(b"k", b"base")
        db.merge(b"k", b"a")
        db.flush()
        db.merge(b"k", b"b")
        assert db.get_merge_operands(b"k") == [b"base", b"a", b"b"]
        assert db.get(b"k") == b"base,a,b"
        db.put(b"plain", b"v")
        assert db.get_merge_operands(b"plain") == [b"v"]
        assert db.get_merge_operands(b"missing") == []
        db.delete(b"k")
        db.merge(b"k", b"after")
        assert db.get_merge_operands(b"k") == [b"after"]


def test_get_merge_operands_snapshot_and_zeroed(tmp_db_path):
    """Review regressions: a post-snapshot range tombstone must not hide the
    chain under the snapshot, and seqno-zeroed survivors stay visible."""
    with DB.open(tmp_db_path, opts(merge_operator=StringAppendOperator(),
                                   disable_auto_compactions=True)) as db:
        db.put(b"k", b"base")
        db.merge(b"k", b"a")
        snap = db.get_snapshot()
        db.delete_range(b"a", b"z")
        db.flush()
        assert db.get_merge_operands(b"k") == []  # covered now
        assert db.get_merge_operands(
            b"k", ReadOptions(snapshot=snap)) == [b"base", b"a"]
        snap.release()
        # Seqno-zeroed value after bottommost compaction stays visible.
        db.put(b"z2", b"zv")
        db.compact_range()
        assert db.get_merge_operands(b"z2") == [b"zv"]


def test_put_get_entity_api(tmp_db_path):
    with DB.open(tmp_db_path, opts()) as db:
        db.put_entity(b"user1", {b"name": b"alice", b"age": b"30"})
        e = db.get_entity(b"user1")
        assert e == {b"name": b"alice", b"age": b"30"}
        db.put(b"plain", b"v")
        assert db.get_entity(b"plain") == {b"": b"v"}
        assert db.get_entity(b"missing") is None
        db.flush()
        db.compact_range()
        assert db.get_entity(b"user1")[b"name"] == b"alice"


def test_set_options_dynamic(tmp_db_path):
    from toplingdb_tpu.utils.config import load_latest_options

    with DB.open(tmp_db_path, opts()) as db:
        db.set_options({"write_buffer_size": 999_999,
                        "disable_auto_compactions": True})
        assert db.options.write_buffer_size == 999_999
        with pytest.raises(InvalidArgument):
            db.set_options({"num_levels": 3})  # immutable
        with pytest.raises(InvalidArgument):
            db.set_options({"no_such_option": 1})
        loaded = load_latest_options(tmp_db_path)
        assert loaded.write_buffer_size == 999_999
        assert loaded.disable_auto_compactions is True
        import os

        n_opts = sum(1 for f in os.listdir(tmp_db_path)
                     if f.startswith("OPTIONS-"))
        assert n_opts == 1, "old OPTIONS file not rolled"


def test_async_multi_get_matches_sync(tmp_db_path):
    """ReadOptions.async_io (fiber-MultiGet analogue): identical results to
    the synchronous batched path across memtable/L0/deep-level sources,
    snapshots, and misses."""
    import random

    o = opts(write_buffer_size=8 * 1024, disable_auto_compactions=True)
    with DB.open(tmp_db_path, o) as db:
        rng = random.Random(6)
        for i in range(3000):
            db.put(b"key%05d" % (i % 2000), b"v%05d" % i)
            if i % 700 == 699:
                db.flush()
        db.compact_range()
        for i in range(0, 2000, 3):
            db.put(b"key%05d" % i, b"mem%05d" % i)  # memtable layer on top
        snap = db.get_snapshot()
        db.delete_range(b"key00100", b"key00300")
        keys = [b"key%05d" % rng.randrange(2500) for _ in range(300)]
        sync = db.multi_get(keys)
        a = db.multi_get(keys, ReadOptions(async_io=True))
        assert a == sync
        ssnap = db.multi_get(keys, ReadOptions(snapshot=snap))
        asnap = db.multi_get(keys, ReadOptions(snapshot=snap, async_io=True))
        assert asnap == ssnap
        snap.release()


def test_persistent_stats_history(tmp_db_path):
    """persist_stats(to_db=True) stores samples in the hidden stats CF;
    they survive reopen (reference persist_stats_to_disk)."""
    from toplingdb_tpu.utils import statistics as st
    from toplingdb_tpu.utils.statistics import Statistics

    o = opts(statistics=Statistics())
    with DB.open(tmp_db_path, o) as db:
        db.put(b"a", b"1")
        db.persist_stats(to_db=True)
        hist = db.get_stats_history(include_persisted=True)
        assert hist and any(
            d.get(st.NUMBER_KEYS_WRITTEN) for _, d in hist
        )
    with DB.open(tmp_db_path, opts(statistics=Statistics())) as db:
        hist = db.get_stats_history(include_persisted=True)
        assert hist, "persisted samples lost on reopen"
        # Hidden CF stays out of the default keyspace.
        it = db.new_iterator()
        it.seek_to_first()
        assert [k for k, _ in it.entries()] == [b"a"]


def test_disable_enable_file_deletions(tmp_db_path):
    import os

    with DB.open(tmp_db_path, opts(disable_auto_compactions=True)) as db:
        for i in range(500):
            db.put(b"k%03d" % i, b"v")
        db.flush()
        old = {f for f in os.listdir(tmp_db_path) if f.endswith(".sst")}
        db.disable_file_deletions()
        db.disable_file_deletions()  # counted
        db.compact_range()
        now = {f for f in os.listdir(tmp_db_path) if f.endswith(".sst")}
        assert old <= now, "obsolete inputs deleted while pinned"
        db.enable_file_deletions()
        db.compact_range()
        still = {f for f in os.listdir(tmp_db_path) if f.endswith(".sst")}
        assert old <= still, "second disable ignored"
        db.enable_file_deletions()
        after = {f for f in os.listdir(tmp_db_path) if f.endswith(".sst")}
        assert not (old & after), "obsolete files kept after enable"
        assert db.get(b"k250") == b"v"
        db.flush_wal(sync=True)


def test_empty_range_delete_is_noop(tmp_db_path):
    """Soak regression: delete_range(begin == end) deletes nothing and must
    not flush a boundless empty table into the MANIFEST."""
    with DB.open(tmp_db_path, opts()) as db:
        db.delete_range(b"k", b"k")       # empty range, empty memtable
        db.flush()                        # must not crash / write junk
        assert db.versions.current.num_files() == 0
        db.put(b"a", b"1")
        db.delete_range(b"z", b"a")       # inverted = empty too
        db.flush()
        assert db.get(b"a") == b"1"
        db.delete_range(b"a", b"a\x00")   # minimal REAL range
        assert db.get(b"a") is None
    with DB.open(tmp_db_path, opts()) as db:
        assert db.get(b"a") is None


def test_get_live_files_and_wal_files(tmp_db_path):
    """GetLiveFiles/GetSortedWalFiles: copying exactly those files yields an
    openable DB (the external-backup contract)."""
    import os
    import shutil

    with DB.open(tmp_db_path, opts(enable_blob_files=True,
                                   min_blob_size=64,
                                   disable_auto_compactions=True)) as db:
        for i in range(300):
            db.put(b"k%04d" % i, b"V" * (100 if i % 3 else 10))
        db.disable_file_deletions()
        try:
            files, manifest_size = db.get_live_files()
            wals = db.get_sorted_wal_files()
            assert any(f.endswith(".sst") for f in files)
            assert any(f.endswith(".blob") for f in files)
            assert "CURRENT" in files
            assert manifest_size > 0
            dst = tmp_db_path + "_copy"
            os.makedirs(dst)
            for f in files + wals:
                shutil.copy2(os.path.join(tmp_db_path, f),
                             os.path.join(dst, f))
                if f.startswith("MANIFEST-"):
                    # Truncate at the snapshot point (the live manifest may
                    # have grown since).
                    with open(os.path.join(dst, f), "r+b") as mf:
                        mf.truncate(manifest_size)
        finally:
            db.enable_file_deletions()
    with DB.open(dst, opts(enable_blob_files=True, min_blob_size=64)) as db2:
        assert db2.get(b"k0100") == b"V" * 100
        assert db2.get(b"k0000") == b"V" * 10


def test_error_handler_severity_taxonomy(tmp_path):
    """Reference ErrorHandler severity mapping (db/error_handler.h:28):
    SOFT keeps foreground writes alive, HARD blocks writes until resume(),
    FATAL/UNRECOVERABLE (corruption / MANIFEST) refuse resume()."""
    from toplingdb_tpu.utils.status import (
        Corruption, IOError_, Severity,
    )

    db = DB.open(str(tmp_path / "db"), Options())
    # SOFT: retryable flush IO error — writes continue, severity visible.
    db._set_background_error(IOError_("enospc", retryable=True), "flush")
    assert db._bg_error_severity == Severity.SOFT_ERROR
    db.put(b"k", b"v")  # foreground writes stay up under SOFT
    assert db.get(b"k") == b"v"
    db.resume()
    assert db.get_property("tpulsm.background-errors") == "0"

    # HARD: non-retryable WAL-adjacent error — writes raise until resume.
    db._set_background_error(IOError_("disk gone"), "wal")
    assert db._bg_error_severity == Severity.HARD_ERROR
    with pytest.raises(IOError_):
        db.put(b"k2", b"v2")
    db.resume()
    db.put(b"k2", b"v2")

    # Escalation: a later worse error replaces a milder one.
    db._set_background_error(IOError_("enospc", retryable=True), "flush")
    db._set_background_error(Corruption("bad block"), "flush")
    assert db._bg_error_severity == Severity.FATAL_ERROR
    with pytest.raises(IOError_):
        db.resume()
    assert db.get_property("tpulsm.bg-error-severity") == "FATAL_ERROR"
    # Reads still work at FATAL; reopen is the way out.
    assert db.get(b"k2") == b"v2"
    db._bg_error = None  # simulate reopen for close()
    db._bg_error_severity = Severity.NO_ERROR
    db.close()

    # UNRECOVERABLE: corruption discovered BY compaction.
    db = DB.open(str(tmp_path / "db2"), Options())
    db._set_background_error(Corruption("merge saw garbage"), "compaction")
    assert db._bg_error_severity == Severity.UNRECOVERABLE
    with pytest.raises(IOError_):
        db.resume()
    db._bg_error = None
    db._bg_error_severity = Severity.NO_ERROR
    db.close()


def test_blob_gc_shrinks_storage_on_overwrite(tmp_db_path):
    """Compaction-time blob GC (reference blob_garbage_collection_age_cutoff
    + BlobFileBuilder rewrite): after overwriting every blob-backed value
    and compacting, dead blob data must be reclaimed — storage shrinks and
    the old blob files are gone (VERDICT r03 item 7 'Done' criterion)."""
    import glob
    import os

    o = opts(enable_blob_files=True, min_blob_size=50,
             enable_blob_garbage_collection=True,
             blob_garbage_collection_age_cutoff=1.0,
             write_buffer_size=1 << 20)
    with DB.open(tmp_db_path, o) as db:
        for i in range(2000):
            db.put(b"k%05d" % i, b"B" * 500)
        db.flush()
        for i in range(2000):
            db.put(b"k%05d" % i, b"C" * 500)
        db.flush()

        def blob_bytes():
            return sum(os.path.getsize(p)
                       for p in glob.glob(tmp_db_path + "/*.blob"))

        before = blob_bytes()
        db.compact_range(None, None)
        db.wait_for_compactions()
        after = blob_bytes()
        assert after < before * 0.6, (before, after)
        for i in range(0, 2000, 97):
            assert db.get(b"k%05d" % i) == b"C" * 500


def test_wide_column_entity_semantics(tmp_db_path):
    """Reference db/wide semantics: PutEntity stores columns; a plain Get
    (and iterator value()) over the entity returns the anonymous default
    column; GetEntity / Iterator.columns() return the full set — across
    flush + compaction."""
    with DB.open(tmp_db_path, opts()) as db:
        db.put_entity(b"e1", {b"": b"defv", b"city": b"paris",
                              b"age": b"30"})
        db.put_entity(b"e2", {b"city": b"rome"})  # no default column
        db.put(b"plain", b"pv")
        assert db.get(b"e1") == b"defv"
        assert db.get(b"e2") == b""
        assert db.get(b"plain") == b"pv"
        db.flush()
        db.compact_range(None, None)
        db.wait_for_compactions()
        assert db.get(b"e1") == b"defv"
        assert db.get_entity(b"e1") == {b"": b"defv", b"city": b"paris",
                                        b"age": b"30"}
        assert db.get_entity(b"plain") == {b"": b"pv"}
        it = db.new_iterator()
        it.seek(b"e1")
        assert it.value() == b"defv"
        assert it.columns()[b"city"] == b"paris"
        it.seek(b"plain")
        assert it.columns() == {b"": b"pv"}
