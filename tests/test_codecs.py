"""Production codecs (snappy/lz4/lz4hc/zstd + ZSTD dictionary) and
per-level compression config — reference
include/rocksdb/compression_type.h:22-28, util/compression.h:1435-1476,
ColumnFamilyOptions::compression_per_level."""

import numpy as np
import pytest

from toplingdb_tpu.db.dbformat import (
    InternalKeyComparator,
    ValueType,
    make_internal_key,
)
from toplingdb_tpu.env import default_env
from toplingdb_tpu.table import format as fmt
from toplingdb_tpu.table.builder import (
    CompressionOptions,
    TableBuilder,
    TableOptions,
)
from toplingdb_tpu.table.reader import TableReader
from toplingdb_tpu.utils import codecs
from toplingdb_tpu.utils.status import Corruption

CODECS = [
    fmt.SNAPPY_COMPRESSION,
    fmt.LZ4_COMPRESSION,
    fmt.LZ4HC_COMPRESSION,
    fmt.ZSTD_COMPRESSION,
]


@pytest.mark.parametrize("ctype", CODECS)
def test_roundtrip(ctype):
    data = b"the quick brown fox " * 200 + bytes(range(256))
    c = fmt.compress(data, ctype)
    assert len(c) < len(data)
    assert fmt.decompress(c, ctype) == data
    # empty + incompressible
    assert fmt.decompress(fmt.compress(b"", ctype), ctype) == b""
    rnd = np.random.default_rng(7).integers(0, 255, 4096, np.uint8).tobytes()
    assert fmt.decompress(fmt.compress(rnd, ctype), ctype) == rnd


@pytest.mark.parametrize("ctype", CODECS)
def test_corrupt_payload_raises(ctype):
    data = b"abcdefgh" * 512
    c = bytearray(fmt.compress(data, ctype))
    c[len(c) // 2] ^= 0xFF
    try:
        out = fmt.decompress(bytes(c), ctype)
        assert out != data  # either raise or produce different bytes
    except Corruption:
        pass


def test_zstd_dictionary_roundtrip():
    samples = [b"user:%04d:profile:common-suffix-xyz" % i for i in range(500)]
    d = codecs.zstd_train_dictionary(samples, 4096)
    assert d  # enough structured samples to train
    blob = b"user:9999:profile:common-suffix-xyz"
    c = codecs.zstd_compress(blob, 3, d)
    assert codecs.zstd_decompress(c, d) == blob
    # wrong dict must not silently succeed with wrong bytes
    with pytest.raises(Corruption):
        codecs.zstd_decompress(c, b"")


@pytest.mark.parametrize("ctype", CODECS)
def test_sst_roundtrip_compressed(tmp_path, ctype):
    env = default_env()
    icmp = InternalKeyComparator()
    p = str(tmp_path / "t.sst")
    w = env.new_writable_file(p)
    opts = TableOptions(compression=ctype, block_size=1024)
    b = TableBuilder(w, icmp, opts)
    for i in range(2000):
        b.add(make_internal_key(b"key%06d" % i, i + 1, ValueType.VALUE),
              b"value-payload-%06d" % i)
    b.finish()
    w.close()
    r = TableReader(env.new_random_access_file(p), icmp, opts)
    it = r.new_iterator()
    it.seek_to_first()
    got = list(it.entries())
    assert len(got) == 2000
    assert got[0][1] == b"value-payload-000000"
    assert got[1999][1] == b"value-payload-001999"


def test_sst_zstd_dict(tmp_path):
    env = default_env()
    icmp = InternalKeyComparator()
    p = str(tmp_path / "d.sst")
    w = env.new_writable_file(p)
    opts = TableOptions(
        compression=fmt.ZSTD_COMPRESSION, block_size=512,
        compression_opts=CompressionOptions(
            max_dict_bytes=4096, zstd_max_train_bytes=1 << 16),
    )
    b = TableBuilder(w, icmp, opts)
    for i in range(4000):
        b.add(make_internal_key(b"key%06d" % i, i + 1, ValueType.VALUE),
              b"shared-prefix-value-%06d-shared-suffix" % i)
    b.finish()
    w.close()
    r = TableReader(env.new_random_access_file(p), icmp, opts)
    assert r._compression_dict  # dict trained and stored
    it = r.new_iterator()
    it.seek_to_first()
    got = list(it.entries())
    assert len(got) == 4000
    assert got[123][1] == b"shared-prefix-value-000123-shared-suffix"
    # point seek through partitions of the file
    it2 = r.new_iterator()
    it2.seek(make_internal_key(b"key003999", 1 << 50, ValueType.VALUE))
    assert it2.valid()


def test_parallel_compression_byte_identical(tmp_path):
    env = default_env()
    icmp = InternalKeyComparator()
    paths = []
    for threads in (1, 4):
        p = str(tmp_path / f"p{threads}.sst")
        w = env.new_writable_file(p)
        opts = TableOptions(compression=fmt.ZSTD_COMPRESSION, block_size=512,
                            compression_parallel_threads=threads)
        b = TableBuilder(w, icmp, opts)
        for i in range(3000):
            b.add(make_internal_key(b"k%06d" % i, i + 1, ValueType.VALUE),
                  b"v" * 40 + b"%d" % i)
        b.finish()
        w.close()
        paths.append(p)
    assert open(paths[0], "rb").read() == open(paths[1], "rb").read()


def test_db_per_level_compression(tmp_path):
    from toplingdb_tpu.db.db import DB
    from toplingdb_tpu.options import Options

    db = DB.open(str(tmp_path / "db"), Options(
        compression_per_level=[fmt.NO_COMPRESSION, fmt.LZ4_COMPRESSION,
                               fmt.ZSTD_COMPRESSION],
        bottommost_compression=fmt.ZSTD_COMPRESSION,
        level0_file_num_compaction_trigger=100,
    ))
    for i in range(3000):
        db.put(b"key%06d" % i, b"payload-%06d" % i * 3)
    db.flush()
    db.compact_range()  # pushes to the bottommost level
    for i in range(0, 3000, 97):
        assert db.get(b"key%06d" % i) == b"payload-%06d" % i * 3
    # the bottommost output really is zstd: reopen the SST and check a
    # data block's type byte
    version = db.versions.cf_current(0)
    lvl, f = max(((lvl, fs[0]) for lvl, fs in enumerate(version.files)
                  if fs), key=lambda t: t[0])
    assert lvl >= 1
    from toplingdb_tpu.db import filename as fn

    raw = open(fn.table_file_name(str(tmp_path / "db"), f.number), "rb").read()
    r = TableReader(db.env.new_random_access_file(
        fn.table_file_name(str(tmp_path / "db"), f.number)), db.icmp,
        TableOptions())
    h = fmt.BlockHandle.decode_exact(
        next(iter(_index_entries(r)))[1])
    assert raw[h.offset + h.size] == fmt.ZSTD_COMPRESSION
    db.close()


def _index_entries(reader):
    it = reader.new_index_iterator()
    it.seek_to_first()
    return it.entries()


def test_options_compression_for_level():
    from toplingdb_tpu.options import Options

    o = Options(compression_per_level=[0, 4, 7])
    assert o.compression_for_level(0) == 0
    assert o.compression_for_level(1) == 4
    assert o.compression_for_level(5) == 7  # past the end: last entry
    o2 = Options(compression=fmt.SNAPPY_COMPRESSION,
                 bottommost_compression=fmt.ZSTD_COMPRESSION)
    assert o2.compression_for_level(3) == fmt.SNAPPY_COMPRESSION
    assert o2.compression_for_level(6, bottommost=True) == fmt.ZSTD_COMPRESSION


def test_dict_training_failure_disables_dict(tmp_path, monkeypatch):
    """ADVICE r2 (high): a failed ZDICT training returns b"" — the same
    value as the 'training pending' sentinel. The replay must DISABLE the
    dict and still write every block (before the fix: the columnar writer
    silently dropped all buffered blocks / recursed; the TableBuilder mixed
    incremental and deferred index entries out of order)."""
    import numpy as np

    from toplingdb_tpu.ops.columnar_io import (ColumnarKV,
                                               write_tables_columnar)
    from toplingdb_tpu.utils import codecs

    monkeypatch.setattr(codecs, "zstd_train_dictionary",
                        lambda samples, cap: b"")
    env = default_env()
    icmp = InternalKeyComparator()
    opts = TableOptions(
        compression=fmt.ZSTD_COMPRESSION, block_size=512,
        compression_opts=CompressionOptions(
            max_dict_bytes=4096, zstd_max_train_bytes=1 << 16),
    )

    # --- TableBuilder path ---
    p = str(tmp_path / "tb.sst")
    w = env.new_writable_file(p)
    b = TableBuilder(w, icmp, opts)
    for i in range(4000):
        b.add(make_internal_key(b"key%06d" % i, i + 1, ValueType.VALUE),
              b"val-%06d-padding-padding" % i)
    props = b.finish()
    w.close()
    assert props.num_data_blocks > 0
    r = TableReader(env.new_random_access_file(p), icmp, opts)
    assert not r._compression_dict
    it = r.new_iterator()
    it.seek_to_first()
    got = list(it.entries())
    assert len(got) == 4000
    assert got[250][1] == b"val-000250-padding-padding"
    # index order intact: a cold point-seek must land correctly
    it2 = r.new_iterator()
    it2.seek(make_internal_key(b"key003500", 1 << 50, ValueType.VALUE))
    assert it2.valid()

    # --- columnar writer path (the silent-data-loss repro shape) ---
    n = 200
    keys = np.frombuffer(
        b"".join(make_internal_key(b"ck%06d" % i, i + 1, ValueType.VALUE)
                 for i in range(n)), dtype=np.uint8).copy()
    vals = np.frombuffer(
        b"".join(b"columnar-value-%06d" % i for i in range(n)),
        dtype=np.uint8).copy()
    kv = ColumnarKV(
        keys, np.arange(n, dtype=np.int32) * 16,
        np.full(n, 16, dtype=np.int32),
        vals, np.arange(n, dtype=np.int32) * 21,
        np.full(n, 21, dtype=np.int32),
    )
    counter = [77]

    def alloc():
        counter[0] += 1
        return counter[0]

    files = write_tables_columnar(
        env, str(tmp_path), alloc, icmp, opts, kv,
        np.arange(n, dtype=np.int32), np.full(n, -1, dtype=np.int64),
        np.full(n, int(ValueType.VALUE), dtype=np.int32),
        np.arange(1, n + 1, dtype=np.uint64), [], creation_time=1,
    )
    assert len(files) == 1
    _fnum, path, cprops, _s, _l, _sel = files[0]
    assert cprops.num_entries == n
    assert cprops.num_data_blocks > 0  # was 0 before the fix (data loss)
    rr = TableReader(env.new_random_access_file(path), icmp, opts)
    it3 = rr.new_iterator()
    it3.seek_to_first()
    got3 = list(it3.entries())
    assert len(got3) == n
    assert got3[42][1] == b"columnar-value-000042"
