"""Parity: native fused merge+GC (tpulsm_merge_gc_runs) vs the two-pass
host twin (sort/merge + host_gc_mask) across randomized run mixes,
snapshots, covers, and complex (MERGE/SINGLE_DELETE) groups — including
the group-aligned splitter logic, forced multi-threaded via
TPULSM_MERGE_THREADS (a 1-CPU box would otherwise never exercise it)."""

import os

import numpy as np
import pytest

from toplingdb_tpu import native
from toplingdb_tpu.db.dbformat import ValueType
from toplingdb_tpu.ops import compaction_kernels as ck

pytestmark = pytest.mark.skipif(
    native.lib() is None or not hasattr(native.lib(), "tpulsm_merge_gc_runs"),
    reason="native fused merge+GC unavailable",
)


def _make_runs(rng, n_runs, per_run, key_space, p_merge=0.0, p_sd=0.0,
               p_del=0.15):
    """Columnar (key_buf, key_offs, key_lens, run_starts, seqs) of sorted
    runs with 8B decimal user keys."""
    bufs = []
    seq_counter = 1
    run_starts = [0]
    total = 0
    for _ in range(n_runs):
        draws = rng.integers(0, key_space, per_run)
        seqs = np.arange(seq_counter, seq_counter + per_run, dtype=np.uint64)
        seq_counter += per_run
        vts = np.full(per_run, int(ValueType.VALUE), dtype=np.uint64)
        r = rng.random(per_run)
        vts[r < p_del] = int(ValueType.DELETION)
        vts[r > 1 - p_merge] = int(ValueType.MERGE)
        vts[(r > p_del) & (r < p_del + p_sd)] = int(
            ValueType.SINGLE_DELETION)
        order = np.lexsort(
            (np.iinfo(np.int64).max - seqs.view(np.int64), draws))
        keys = []
        for i in order:
            uk = b"%08d" % draws[i]
            packed = (int(seqs[i]) << 8) | int(vts[i])
            keys.append(uk + packed.to_bytes(8, "little"))
        bufs.extend(keys)
        total += per_run
        run_starts.append(total)
    key_buf = np.frombuffer(b"".join(bufs), dtype=np.uint8)
    key_lens = np.full(total, 16, dtype=np.int64)
    key_offs = np.arange(total, dtype=np.int64) * 16
    return key_buf, key_offs, key_lens, np.array(run_starts, dtype=np.int64)


def _two_pass(key_buf, key_offs, key_lens, snapshots, bottommost, cover,
              run_starts):
    """The pre-fusion reference pipeline (native sort + numpy masks)."""
    s, new_key, seq, vtype = ck.host_sort_with_boundaries(
        key_buf, key_offs, key_lens, 8, run_starts=run_starts)
    keep, zero_seq, host_resolve, _ = ck.host_gc_mask(
        new_key, seq[s], vtype[s], snapshots,
        None if cover is None else cover[s], bottommost)
    out = keep | host_resolve
    order = s[out].astype(np.int32)
    return (order, zero_seq[out], host_resolve[out],
            bool(host_resolve.any()), seq, vtype)


@pytest.mark.parametrize("threads", [1, 4])
@pytest.mark.parametrize("case", [
    dict(n_runs=4, per_run=3000, key_space=1500, snaps=[], bottom=True),
    dict(n_runs=4, per_run=3000, key_space=1500, snaps=[2000, 7000],
         bottom=True),
    dict(n_runs=3, per_run=2000, key_space=400, snaps=[500, 1500, 3000],
         bottom=False),
    dict(n_runs=2, per_run=2500, key_space=800, snaps=[], bottom=True,
         p_merge=0.05, p_sd=0.03),
    dict(n_runs=5, per_run=1000, key_space=50, snaps=[1200], bottom=True,
         p_merge=0.02),
    dict(n_runs=4, per_run=1500, key_space=99999999, snaps=[], bottom=True),
])
def test_fused_matches_two_pass(case, threads, monkeypatch):
    monkeypatch.setenv("TPULSM_MERGE_THREADS", str(threads))
    rng = np.random.default_rng(42 + threads)
    kb, ko, kl, rs = _make_runs(
        rng, case["n_runs"], case["per_run"], case["key_space"],
        p_merge=case.get("p_merge", 0.0), p_sd=case.get("p_sd", 0.0))
    cover = None
    if case.get("with_cover"):
        cover = rng.integers(0, 5000, len(ko)).astype(np.uint64)
    got = ck.host_merge_gc(kb, ko, kl, case["snaps"], case["bottom"],
                           cover, rs)
    assert got is not None
    want = _two_pass(kb, ko, kl, case["snaps"], case["bottom"], cover, rs)
    np.testing.assert_array_equal(got[0], want[0], err_msg="order")
    # Two-pass zero flags on complex rows are PROVISIONAL (the caller
    # masks them with ~cx before use); the fused path emits the effective
    # value directly — compare post-mask semantics.
    np.testing.assert_array_equal(got[1], want[1] & ~want[2],
                                  err_msg="zero")
    np.testing.assert_array_equal(got[2], want[2], err_msg="cx")
    assert got[3] == want[3]
    np.testing.assert_array_equal(got[4], want[4], err_msg="seq")
    np.testing.assert_array_equal(got[5], want[5], err_msg="vtype")


@pytest.mark.parametrize("threads", [1, 4])
def test_fused_with_cover(threads, monkeypatch):
    """Range-tombstone cover input: covered rows drop unless complex."""
    monkeypatch.setenv("TPULSM_MERGE_THREADS", str(threads))
    rng = np.random.default_rng(7)
    kb, ko, kl, rs = _make_runs(rng, 4, 2000, 600, p_merge=0.02)
    cover = rng.integers(0, 9000, len(ko)).astype(np.uint64)
    cover[rng.random(len(ko)) < 0.5] = 0
    for snaps in ([], [3000], [1000, 5000]):
        got = ck.host_merge_gc(kb, ko, kl, snaps, True, cover, rs)
        want = _two_pass(kb, ko, kl, snaps, True, cover, rs)
        np.testing.assert_array_equal(got[0], want[0])
        np.testing.assert_array_equal(got[1], want[1] & ~want[2])
        np.testing.assert_array_equal(got[2], want[2])


def test_fused_ineligible_long_keys():
    """>8B user keys must return None (two-pass path handles them)."""
    keys = [b"averylongkey1" + (1 << 8 | 1).to_bytes(8, "little"),
            b"averylongkey2" + (2 << 8 | 1).to_bytes(8, "little")]
    kb = np.frombuffer(b"".join(keys), dtype=np.uint8)
    kl = np.full(2, 21, dtype=np.int64)
    ko = np.arange(2, dtype=np.int64) * 21
    rs = np.array([0, 1, 2], dtype=np.int64)
    assert ck.host_merge_gc(kb, ko, kl, [], True, None, rs) is None
