"""The C-extension read fast path (native/fastget.c) under concurrency:
readers on multiple threads race writers, flushes, and compactions; every
acknowledged write must stay visible and no stale state may be served
across memtable switches / version installs."""

import random
import threading

import pytest

from toplingdb_tpu import native
from toplingdb_tpu.db.db import DB
from toplingdb_tpu.options import Options

pytestmark = pytest.mark.skipif(native.fastget() is None,
                                reason="fastget extension unavailable")


def test_threaded_reads_race_writes_and_flushes(tmp_path):
    db = DB.open(str(tmp_path / "db"),
                 Options(create_if_missing=True, write_buffer_size=64 << 10))
    n_keys = 4000
    for i in range(n_keys):
        db.put(b"k%05d" % i, b"v0-%05d" % i)
    db.flush()
    stop = threading.Event()
    errs = []

    def writer():
        try:
            gen = 1
            while not stop.is_set():
                for i in range(0, n_keys, 7):
                    db.put(b"k%05d" % i, b"v%d-%05d" % (gen, i))
                gen += 1
        except Exception as e:  # noqa: BLE001
            errs.append(e)

    def reader(seed):
        rng = random.Random(seed)
        try:
            while not stop.is_set():
                i = rng.randrange(n_keys)
                v = db.get(b"k%05d" % i)
                assert v is not None and v.endswith(b"-%05d" % i), (i, v)
                ks = [b"k%05d" % rng.randrange(n_keys) for _ in range(32)]
                for k, v in zip(ks, db.multi_get(ks)):
                    assert v is not None and v.endswith(k[1:]), (k, v)
        except Exception as e:  # noqa: BLE001
            errs.append(e)

    threads = [threading.Thread(target=writer)] + [
        threading.Thread(target=reader, args=(s,)) for s in range(3)
    ]
    for t in threads:
        t.start()
    import time

    time.sleep(4.0)
    stop.set()
    for t in threads:
        t.join()
    db.wait_for_compactions()
    assert not errs, errs[0]
    # Final state coherent after the churn.
    for i in range(0, n_keys, 97):
        v = db.get(b"k%05d" % i)
        assert v is not None and v.endswith(b"-%05d" % i)
    db.close()
